"""Pipelined TCP routing front-end for the serving replica fleet.

The router speaks the existing serving wire protocol on both sides
(`serving/server.py` frame layout, verbatim), so clients need zero
changes: point a :class:`~dmlc_core_tpu.serving.client.PredictClient`
at the router and every request fans out across replicas.  Per-request
req_ids are rewritten on the backend leg (client ids are only unique
per connection; the fleet needs them unique per replica link) and
restored on the way back; ``trace_id``/``parent_span`` pass through
untouched, with a ``serving.router.request`` span spliced between the
client's and the replica's.

**Replica selection** is least-loaded power-of-two-choices: sample two
candidates, send to the one with the lower ``inflight + 8 ×
queue_fraction`` score (router-local inflight is instant; the
queue-depth fraction from the replica's ``/healthz`` body ages up to a
poll interval).  The candidate set is filtered hard before sampling:

* ``overloaded`` replicas and replicas whose per-replica
  :class:`~dmlc_core_tpu.utils.retry.CircuitBreaker` is open are out;
* ``degraded`` replicas are **drained** — eligible only when no ``ok``
  replica remains (the `/healthz` degrade signal exists precisely so
  the balancer backs off before the shed cliff);
* replicas flagged by the tracker-side straggler board
  (`telemetry/anomaly.py`, via the registry's heartbeat state pushes)
  are evicted from rotation until the flag clears;
* a model-tagged connection (HELLO preamble) only considers replicas
  serving that ``model_id``.

**Retry budget** is replica-aware: a shed (OVERLOADED), a draining
replica's SHUTDOWN answer, or a lost backend connection triggers an
immediate hedged resubmit to a *different* replica (the ``tried`` set
grows per attempt) under the ``DMLC_ROUTER_RETRIES`` budget
(:meth:`RetryPolicy.from_env`).  There is deliberately **no backoff
sleep** on this path — the resubmit IS the backoff, because it lands
on a replica whose queue the router already believes is shorter; a
sleeping reader thread would head-of-line-block every other response
on that replica link.  Non-idempotent rejects (BAD_REQUEST, TOO_LARGE,
DEADLINE_EXCEEDED) are **never** retried — they pass through verbatim.

Membership comes from either a static replica list or a
:class:`~.registry.ReplicaRegistry` (``list_replicas`` sync at
``DMLC_ROUTER_SYNC_INTERVAL``); replica ``/healthz`` bodies are polled
directly at ``DMLC_ROUTER_HEALTH_INTERVAL`` for fresher load signal
than heartbeat cadence provides.

**Registry HA (r17).**  ``registry`` accepts an ordered endpoint list —
a ``(host, port)`` tuple, a ``"host:port,host:port"`` string, or the
``DMLC_ROUTER_REGISTRY`` env var — wrapped in a
:class:`~dmlc_core_tpu.transport.endpoints.EndpointSet`: sticky
failover with a per-endpoint circuit breaker, and client-side
``control_epoch`` fencing so a reply from a fenced ex-primary is
treated as a failure.  Between successful syncs the router serves the
last-known fleet (stale-while-revalidate): requests keep flowing on the
cached replica map while the sync loop revalidates in the background,
and ``/healthz`` reports the cache age as ``replica_view_age_s``.
"""

from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ...parallel.tracker import jittered
from ...telemetry import sampling as telsampling
from ...telemetry import trace as teltrace
from ...telemetry.wide_events import wide_event
from ...transport.endpoints import EndpointSet, EndpointsLike
from ...transport.frames import send_all
from ...transport.listener import Listener, reuseport_group, \
    serve_connection
from ...transport.reactor import reactor_loops, reactor_opt_in
from ...telemetry.exposition import TelemetryServer
from ...utils.logging import DMLCError, get_logger, log_info
from ...utils.metrics import metrics
from ...utils.parameter import get_env
from ...utils.retry import CircuitBreaker, CircuitOpen, RetryPolicy
from ..server import (HELLO_REQ_ID, REQ_HEADER, RSP_HEADER,
                      STATUS_NAMES, STATUS_OK, STATUS_OVERLOADED,
                      STATUS_SHUTDOWN, _MAX_NNZ, _MAX_ROWS,
                      _recv_exact, pack_hello)
from .registry import fleet_rpc

__all__ = ["ServingRouter"]

logger = get_logger()

#: queue_fraction's weight against router-local inflight in the
#: load score: a full replica queue counts like 8 in-flight requests
_QUEUE_WEIGHT = 8.0

STATUS_BAD_REQUEST = 5          # mirror of server.STATUS_BAD_REQUEST


class _ClientConn:
    """One front-side client connection: write lock + the model tag its
    HELLO (if any) declared."""

    __slots__ = ("cid", "sock", "wlock", "model_id", "alive")

    def __init__(self, cid: int, sock: socket.socket):
        self.cid = cid
        self.sock = sock
        self.wlock = threading.Lock()
        self.model_id = "default"
        self.alive = True

    def respond(self, req_id: int, status: int, payload: bytes) -> None:
        n = len(payload) // 4 if status == STATUS_OK else len(payload)
        try:
            with self.wlock:
                send_all(self.sock, RSP_HEADER.pack(req_id, status, n)
                         + payload)
        except OSError:
            self.alive = False   # reader thread owns the cleanup


class _Pending:
    """One in-flight request: enough to forward the answer back and to
    replay the frame tail against a different replica."""

    __slots__ = ("bid", "client", "client_req_id", "trace_id",
                 "parent_span", "rows", "nnz", "tail", "attempts",
                 "tried", "replica_key", "span", "hedges", "failovers",
                 "t0")

    def __init__(self, bid: int, client: _ClientConn, client_req_id: int,
                 trace_id: int, parent_span: int, rows: int, nnz: int,
                 tail: bytes, span: Optional[Any]):
        self.bid = bid
        self.client = client
        self.client_req_id = client_req_id
        self.trace_id = trace_id
        self.parent_span = parent_span
        self.rows = rows
        self.nnz = nnz
        self.tail = tail
        self.attempts = 0
        self.tried: set = set()
        self.replica_key: Optional[str] = None
        self.span = span
        self.hedges = 0          # status-triggered resubmits (shed/shutdown)
        self.failovers = 0       # conn-lost / transport-walk replacements
        self.t0 = time.monotonic()


class _Replica:
    """Router-side view of one backend replica: membership facts from
    the registry/static list, load facts from ``/healthz`` polls, plus
    the lazy backend connection and its reader."""

    def __init__(self, key: str, host: str, port: int, *,
                 health_port: Optional[int] = None,
                 model_id: str = "default",
                 jobid: Optional[str] = None):
        self.key = key
        self.host = host
        self.port = int(port)
        self.health_port = health_port
        self.model_id = model_id
        self.jobid = jobid or key
        self.state = "ok"            # ok | degraded | overloaded
        self.queue_fraction = 0.0
        self.alive = True
        self.straggler = False
        self.inflight = 0            # router-local, under self.lock
        # per-replica breaker: a replica that keeps failing fast-fails
        # locally instead of eating the whole retry budget every request
        self.breaker = CircuitBreaker.from_env(
            "DMLC_ROUTER", name=f"router.{key}")
        self.lock = threading.Lock()
        self.wlock = threading.Lock()
        self.sock: Optional[socket.socket] = None
        self.fabric_connected = False   # reactor-mode pooled-link flag
        self.outstanding: set = set()   # backend ids, under self.lock

    def load_score(self) -> float:
        return self.inflight + _QUEUE_WEIGHT * self.queue_fraction


class ServingRouter:
    """Serving-protocol front-end over N replicas.

    >>> router = ServingRouter(registry=reg.address).start()
    >>> client = PredictClient(router.host, router.port)

    ``registry`` (a ``(host, port)`` tuple, a ``"host:port,host:port"``
    string, or a list of either — primary first, standbys after)
    enables dynamic membership, straggler flags and the ``/rollouts``
    proxy; when omitted, ``DMLC_ROUTER_REGISTRY`` supplies the list.
    ``replicas`` pins a static fleet (items ``(host, port)`` or
    ``(host, port, health_port)``) for registry-less deployments — both
    may be given, the registry view then overlays the static seed.
    """

    def __init__(self, *, host: str = "127.0.0.1", port: int = 0,
                 registry: Optional[EndpointsLike] = None,
                 replicas: Optional[List[tuple]] = None,
                 telemetry_port: Optional[int] = None,
                 health_poll_s: Optional[float] = None,
                 sync_s: Optional[float] = None,
                 backlog: int = 64,
                 reactor: Optional[bool] = None):
        if registry is None:
            registry = get_env("DMLC_ROUTER_REGISTRY", "") or None
        if registry is None and not replicas:
            raise DMLCError("ServingRouter needs a registry address "
                            "(arg or DMLC_ROUTER_REGISTRY) or a static "
                            "replica list")
        self._registry: Optional[EndpointSet] = (
            None if registry is None
            else EndpointSet(registry, env_prefix="DMLC_ROUTER",
                             name="router.registry"))
        # compat alias: the preferred primary as a plain tuple
        self.registry_addr = (None if self._registry is None
                              else self._registry.primary)
        self._last_sync = 0.0        # time.monotonic() of last good sync
        if health_poll_s is None:
            health_poll_s = get_env("DMLC_ROUTER_HEALTH_INTERVAL", 0.5)
        if sync_s is None:
            sync_s = get_env("DMLC_ROUTER_SYNC_INTERVAL", 1.0)
        self.health_poll_s = max(0.05, float(health_poll_s))
        self.sync_s = max(0.05, float(sync_s))
        self._retry = RetryPolicy.from_env("DMLC_ROUTER",
                                           name="serving.router")
        self._rlock = threading.Lock()      # guards _replicas map shape
        self._replicas: Dict[str, _Replica] = {}
        for item in replicas or []:
            h, p = item[0], int(item[1])
            hp = int(item[2]) if len(item) > 2 and item[2] is not None \
                else None
            key = f"{h}:{p}"
            self._replicas[key] = _Replica(key, h, p, health_port=hp)
        self._plock = threading.Lock()      # guards _pending + _next_bid
        self._pending: Dict[int, _Pending] = {}
        self._next_bid = 0
        self._conns: Dict[int, _ClientConn] = {}
        self._conn_lock = threading.Lock()
        self._next_conn = 0
        self._stopping = False
        self._stop_ev = threading.Event()
        self._threads: List[threading.Thread] = []
        self._m_requests = metrics.counter("serving.router.requests")
        self._m_retries = metrics.counter("serving.router.retries")
        self._m_sheds = metrics.counter("serving.router.sheds")
        self._m_inflight = metrics.gauge("serving.router.inflight")
        # same tail-sampling config as the replicas behind us: the hash
        # floor is consistent on trace_id, so verdicts agree tier-to-tier
        telsampling.maybe_install_from_env()
        # the fabric switch must resolve *before* bind: N reactor loops
        # need N SO_REUSEPORT siblings, and that option only works when
        # set pre-bind
        self._reactor_mode = reactor_opt_in(reactor)
        n_loops = reactor_loops() if self._reactor_mode else 1
        if self._reactor_mode and n_loops > 1:
            self._listeners = reuseport_group(host, port, n_loops,
                                              backlog=backlog)
        else:
            self._listeners = [Listener(host, port, backlog=backlog)]
        self._srv = self._listeners[0].sock     # compat alias
        self.host, self.port = (self._listeners[0].host,
                                self._listeners[0].port)
        self._fabric = None     # RouterFabric once start()ed (reactor mode)
        if telemetry_port is None:
            p = get_env("DMLC_ROUTER_METRICS_PORT", -1)
            telemetry_port = p if p >= 0 else None
        self.telemetry: Optional[TelemetryServer] = None
        if telemetry_port is not None:
            self.telemetry = TelemetryServer(
                port=int(telemetry_port),
                health_fn=self.health_doc,
                fleet_fn=self.fleet_snapshot,
                rollouts_fn=(self._rollouts_proxy
                             if self.registry_addr else None))

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServingRouter":
        if self.registry_addr is not None:
            self.sync_replicas()           # first sync before serving
        if self._reactor_mode:
            from .reactor_router import RouterFabric
            self._fabric = RouterFabric(self, self._listeners)
            self._fabric.start()
        else:
            self._threads.append(self._listeners[0].spawn(
                self._on_client_conn, name="router-accept",
                stopping=lambda: self._stopping))
        t = threading.Thread(target=self._health_loop,
                             name="router-health", daemon=True)
        t.start()
        self._threads.append(t)
        if self.registry_addr is not None:
            t = threading.Thread(target=self._sync_loop,
                                 name="router-sync", daemon=True)
            t.start()
            self._threads.append(t)
        if self.telemetry is not None:
            self.telemetry.start()
        log_info("serving router on %s:%d over %d replica(s)%s",
                 self.host, self.port, len(self._replicas),
                 " [reactor]" if self._reactor_mode else "")
        return self

    def stop(self) -> None:
        self._stopping = True
        self._stop_ev.set()
        if self.telemetry is not None:
            self.telemetry.stop()
        for lst in self._listeners:
            lst.close()
        if self._fabric is not None:
            self._fabric.stop()     # closes client + pooled replica conns
        with self._conn_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            for closer in (lambda: c.sock.shutdown(socket.SHUT_RDWR),
                           c.sock.close):
                try:
                    closer()
                except OSError:
                    pass
        with self._rlock:
            reps = list(self._replicas.values())
        for rep in reps:
            self._kill_backend(rep)
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- membership ------------------------------------------------------
    def _registry_rpc(self, msg: dict, timeout: float = 5.0) -> dict:
        """One registry round trip over the endpoint set: sticky
        failover across standbys, breaker-gated, fencing-aware."""
        assert self._registry is not None
        return self._registry.call(
            lambda addr: fleet_rpc(addr, msg, timeout=timeout))

    def sync_replicas(self) -> None:
        """One registry round trip: overlay membership, health,
        straggler and liveness flags onto the local replica map."""
        listing = self._registry_rpc({"cmd": "list_replicas"})["replicas"]
        seen = set()
        with self._rlock:
            for r in listing:
                key = f"{r['host']}:{r['port']}"
                seen.add(key)
                rep = self._replicas.get(key)
                if rep is None:
                    rep = _Replica(key, r["host"], int(r["port"]),
                                   health_port=r.get("health_port"),
                                   model_id=r.get("model_id") or "default",
                                   jobid=r.get("jobid"))
                    self._replicas[key] = rep
                    log_info("router: replica %s joined (model=%s)",
                             key, rep.model_id)
                rep.health_port = r.get("health_port", rep.health_port)
                rep.model_id = r.get("model_id") or rep.model_id
                rep.alive = bool(r.get("alive", True))
                rep.straggler = bool(r.get("straggler", False))
                # heartbeat-fed load facts; the direct /healthz poll
                # overwrites these with fresher numbers when it can
                rep.state = r.get("health", rep.state)
                rep.queue_fraction = float(r.get("queue_fraction", 0.0))
            gone = [k for k in self._replicas if k not in seen]
            dropped = [self._replicas.pop(k) for k in gone]
        for rep in dropped:
            log_info("router: replica %s left the registry", rep.key)
            self._kill_backend(rep)
        self._last_sync = time.monotonic()
        metrics.gauge("serving.router.replicas").set(len(listing))

    def _sync_loop(self) -> None:
        down = False
        while not self._stop_ev.wait(jittered(self.sync_s)):
            try:
                self.sync_replicas()
                down = False
            except (OSError, DMLCError) as e:
                if not down:    # one line per registry outage, not per tick
                    down = True
                    logger.warning("router: registry sync failed (%s) — "
                                   "serving last-known fleet", e)

    def _health_loop(self) -> None:
        while not self._stop_ev.wait(jittered(self.health_poll_s)):
            with self._rlock:
                reps = list(self._replicas.values())
            for rep in reps:
                if rep.health_port is None:
                    continue
                try:
                    conn = http.client.HTTPConnection(
                        rep.host, int(rep.health_port), timeout=2.0)
                    try:
                        conn.request("GET", "/healthz")
                        doc = json.loads(conn.getresponse().read())
                    finally:
                        conn.close()
                except (OSError, ValueError):
                    continue    # liveness is the registry's call, not ours
                if isinstance(doc, dict):
                    rep.state = str(doc.get("status", rep.state))
                    rep.queue_fraction = float(
                        doc.get("queue_fraction", rep.queue_fraction))

    # -- replica selection -----------------------------------------------
    def _pick(self, model_id: str, tried: set) -> Optional[_Replica]:
        """Least-loaded pick-2 over the filtered candidate set; degraded
        replicas drain (chosen only when nothing is ``ok``)."""
        with self._rlock:
            reps = list(self._replicas.values())
        ok: List[_Replica] = []
        degraded: List[_Replica] = []
        for rep in reps:
            if (rep.key in tried or not rep.alive or rep.straggler
                    or rep.model_id != model_id
                    or rep.state == "overloaded"
                    or rep.breaker.state == "open"):
                continue
            (ok if rep.state == "ok" else degraded).append(rep)
        pool = ok or degraded
        if not pool:
            return None
        if len(pool) == 1:
            return pool[0]
        a, b = random.sample(pool, 2)
        return a if a.load_score() <= b.load_score() else b

    # -- backend link ----------------------------------------------------
    def _ensure_backend(self, rep: _Replica) -> socket.socket:
        with rep.lock:
            if rep.sock is not None:
                return rep.sock
            sock = socket.create_connection((rep.host, rep.port),
                                            timeout=5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
            rep.sock = sock
        # declare our model expectation; a mismatched replica answers
        # BAD_REQUEST and drops the link, which surfaces as a failover
        with rep.wlock:
            send_all(sock, pack_hello(rep.model_id))
        serve_connection(self._backend_read_loop, rep, sock,
                         name=f"router-backend-{rep.key}")
        return sock

    def _kill_backend(self, rep: _Replica) -> None:
        if self._fabric is not None:
            self._fabric.drop_backend(rep)
        with rep.lock:
            sock, rep.sock = rep.sock, None
        if sock is not None:
            for closer in (lambda: sock.shutdown(socket.SHUT_RDWR),
                           sock.close):
                try:
                    closer()
                except OSError:
                    pass

    def _backend_read_loop(self, rep: _Replica,
                           sock: socket.socket) -> None:
        try:
            while True:
                head = _recv_exact(sock, RSP_HEADER.size)
                if head is None:
                    raise DMLCError("replica closed the connection")
                bid, status, n = RSP_HEADER.unpack(head)
                payload = _recv_exact(sock, 4 * n if status == STATUS_OK
                                      else n)
                if payload is None:
                    raise DMLCError("replica died mid-response")
                if bid == HELLO_REQ_ID:
                    raise DMLCError(
                        "replica refused model hello: "
                        + payload.decode("utf-8", "replace"))
                self._on_backend_response(rep, bid, status, payload)
        except (OSError, DMLCError) as e:
            self._on_backend_lost(rep, sock, e)

    def _on_backend_response(self, rep: _Replica, bid: int, status: int,
                             payload: bytes) -> None:
        with self._plock:
            pend = self._pending.get(bid)
        if pend is None:
            return               # answered by an earlier failover path
        # OVERLOADED and SHUTDOWN are idempotent rejects — the replica
        # did no work — so a hedged resubmit to a different replica is
        # safe; every other status is final and passes through verbatim
        if (status in (STATUS_OVERLOADED, STATUS_SHUTDOWN)
                and self._try_failover(pend, rep,
                                       reason=STATUS_NAMES.get(status))):
            return
        with self._plock:
            self._pending.pop(bid, None)
        self._release(rep, bid)
        if status == STATUS_OK:
            rep.breaker.record_success()
        elif status == STATUS_OVERLOADED:
            self._m_sheds.add(1)
        outcome = STATUS_NAMES.get(status, str(status))
        if pend.span is not None:
            pend.span.end(status=outcome, attempts=pend.attempts,
                          replica=rep.key)
        pend.client.respond(pend.client_req_id, status, payload)
        wide_event("serving.route", model=pend.client.model_id,
                   replica=rep.key, req_id=pend.client_req_id,
                   rows=pend.rows, nnz=pend.nnz, outcome=outcome,
                   attempts=pend.attempts, hedges=pend.hedges,
                   failovers=pend.failovers,
                   dur_ms=round((time.monotonic() - pend.t0) * 1e3, 3),
                   trace_id=(teltrace.format_id(pend.trace_id)
                             if pend.trace_id else None))

    def _on_backend_lost(self, rep: _Replica, sock: socket.socket,
                         exc: BaseException) -> None:
        with rep.lock:
            if rep.sock is not sock:
                stale = True     # a newer link owns the replica now
            else:
                stale = False
                rep.sock = None
            orphans = list(rep.outstanding)
            rep.outstanding.clear()
            rep.inflight = 0
        try:
            sock.close()
        except OSError:
            pass
        if stale and not orphans:
            return
        if not self._stopping:
            rep.breaker.record_failure()
            logger.warning("router: lost replica %s (%s) — refanning %d "
                           "in-flight request(s)", rep.key, exc,
                           len(orphans))
        for bid in orphans:
            with self._plock:
                pend = self._pending.get(bid)
            if pend is None:
                continue
            metrics.counter("serving.router.failovers").add(1)
            if not self._try_failover(pend, rep, reason="conn_lost",
                                      already_released=True):
                with self._plock:
                    self._pending.pop(bid, None)
                self._respond_shed(pend, f"replica {rep.key} lost: {exc}")

    # -- dispatch / retry ------------------------------------------------
    def _release(self, rep: _Replica, bid: int) -> None:
        with rep.lock:
            rep.outstanding.discard(bid)
            rep.inflight = max(0, rep.inflight - 1)

    def _respond_shed(self, pend: _Pending, msg: str) -> None:
        self._m_sheds.add(1)
        if pend.span is not None:
            pend.span.end(status="OVERLOADED", attempts=pend.attempts)
        pend.client.respond(pend.client_req_id, STATUS_OVERLOADED,
                            msg.encode("utf-8", "replace"))
        wide_event("serving.route", model=pend.client.model_id,
                   req_id=pend.client_req_id, rows=pend.rows,
                   nnz=pend.nnz, outcome="OVERLOADED",
                   attempts=pend.attempts, hedges=pend.hedges,
                   failovers=pend.failovers,
                   dur_ms=round((time.monotonic() - pend.t0) * 1e3, 3),
                   trace_id=(teltrace.format_id(pend.trace_id)
                             if pend.trace_id else None))

    def _hedge_target(self, pend: _Pending, failed: _Replica, *,
                      reason: Optional[str],
                      already_released: bool = False
                      ) -> Optional[_Replica]:
        """Budget check + replacement pick + hedge/failover bookkeeping
        — the transport-free half of a resubmit, shared by the threaded
        and reactor dispatch paths.  ``None`` means the caller answers
        the client itself."""
        if not already_released:
            self._release(failed, pend.bid)
        if pend.attempts >= self._retry.max_attempts:
            return None
        target = self._pick(pend.client.model_id, pend.tried)
        if target is None:
            return None
        self._m_retries.add(1)
        # name the two resubmit flavours apart: a status-triggered
        # resubmit (OVERLOADED/SHUTDOWN — the replica did no work) is a
        # *hedge*; a lost connection is a *failover* proper.  Both carry
        # endpoint labels, and the replacement attempt reuses
        # pend.parent_span, so every attempt re-parents under the one
        # original serving.router.request span.
        kind = "failover" if reason == "conn_lost" else "hedge"
        if kind == "hedge":
            pend.hedges += 1
        else:
            pend.failovers += 1
        if pend.span is not None:
            pend.span.event(kind, frm=failed.key, to=target.key,
                            reason=reason)
        return target

    def _try_failover(self, pend: _Pending, failed: _Replica, *,
                      reason: Optional[str],
                      already_released: bool = False) -> bool:
        """Resubmit ``pend`` to a different replica if the budget and
        the candidate set allow; True when the request found a new home
        (or was re-queued), False when the caller must answer."""
        target = self._hedge_target(pend, failed, reason=reason,
                                    already_released=already_released)
        if target is None:
            return False
        return self._dispatch_any(pend, target)

    def _dispatch_any(self, pend: _Pending, rep: _Replica) -> bool:
        """Route the transport step to whichever fabric is live."""
        if self._fabric is not None:
            return self._fabric.dispatch(pend, rep)
        return self._dispatch(pend, rep)

    def _make_pending(self, bid: int, client, client_req_id: int,
                      trace_id: int, parent_span: int, rows: int,
                      nnz: int, tail: bytes, span) -> _Pending:
        """Factory for the reactor fabric (``_Pending`` is module-
        private; the duck-typed ``client`` just needs ``respond``/
        ``model_id``/``alive``)."""
        return _Pending(bid, client, client_req_id, trace_id,
                        parent_span, rows, nnz, tail, span)

    def _dispatch(self, pend: _Pending, rep: _Replica) -> bool:
        """Send ``pend`` to ``rep``; on transport failure walk the
        remaining candidates.  True iff the frame reached some replica's
        socket (the reader owns it from there)."""
        while True:
            pend.attempts += 1
            pend.tried.add(rep.key)
            pend.replica_key = rep.key
            try:
                rep.breaker.allow()
                sock = self._ensure_backend(rep)
                with rep.lock:
                    rep.outstanding.add(pend.bid)
                    rep.inflight += 1
                frame = REQ_HEADER.pack(pend.bid, pend.trace_id,
                                        pend.parent_span, pend.rows,
                                        pend.nnz) + pend.tail
                with rep.wlock:
                    send_all(sock, frame)
                return True
            except (OSError, CircuitOpen) as e:
                self._release(rep, pend.bid)
                if not isinstance(e, CircuitOpen):
                    rep.breaker.record_failure()
                    self._kill_backend(rep)
                nxt = None
                if pend.attempts < self._retry.max_attempts:
                    nxt = self._pick(pend.client.model_id, pend.tried)
                if nxt is None:
                    return False
                self._m_retries.add(1)
                pend.failovers += 1
                if pend.span is not None:
                    pend.span.event("failover", frm=rep.key, to=nxt.key,
                                    reason=type(e).__name__)
                rep = nxt

    # -- frontend (threaded fallback; reactor mode lives in
    # reactor_router.RouterFabric) ---------------------------------------
    def _on_client_conn(self, sock: socket.socket, _addr) -> None:
        with self._conn_lock:
            cid = self._next_conn
            self._next_conn += 1
            conn = _ClientConn(cid, sock)
            self._conns[cid] = conn
        serve_connection(self._serve_conn, conn,
                         name=f"router-conn-{cid}")

    def _serve_conn(self, conn: _ClientConn) -> None:
        sock = conn.sock
        try:
            while True:
                head = _recv_exact(sock, REQ_HEADER.size)
                if head is None:
                    return
                req_id, trace_id, parent_span, rows, nnz = \
                    REQ_HEADER.unpack(head)
                if req_id == HELLO_REQ_ID:
                    blob = _recv_exact(sock, nnz)
                    if blob is None:
                        return
                    conn.model_id = blob.decode("utf-8",
                                                "replace") or "default"
                    continue
                if rows == 0 or rows > _MAX_ROWS or nnz > _MAX_NNZ:
                    conn.respond(req_id, STATUS_BAD_REQUEST,
                                 f"bad header rows={rows} "
                                 f"nnz={nnz}".encode())
                    return
                tail = _recv_exact(sock, 4 * (rows + 1) + 8 * nnz)
                if tail is None:
                    return
                self._m_requests.add(1)
                span = None
                if trace_id:
                    span = teltrace.start_span(
                        "serving.router.request",
                        parent=teltrace.TraceContext(trace_id,
                                                     parent_span),
                        req_id=req_id, rows=rows, model=conn.model_id)
                with self._plock:
                    bid = self._next_bid
                    self._next_bid += 1
                pend = _Pending(bid, conn, req_id, trace_id, parent_span,
                                rows, nnz, tail, span)
                # the replica-side span parents on the ROUTER span, so
                # client → router → replica → engine chain in one trace
                if span is not None:
                    pend.trace_id = span.context.trace_id
                    pend.parent_span = span.context.span_id
                with self._plock:
                    self._pending[bid] = pend
                    self._m_inflight.set(len(self._pending))
                target = self._pick(conn.model_id, pend.tried)
                if target is None or not self._dispatch(pend, target):
                    with self._plock:
                        self._pending.pop(bid, None)
                    self._respond_shed(
                        pend, f"no replica available for model "
                              f"{conn.model_id!r}")
        except OSError:
            pass
        finally:
            conn.alive = False
            with self._conn_lock:
                self._conns.pop(conn.cid, None)
            try:
                sock.close()
            except OSError:
                pass

    # -- observability ---------------------------------------------------
    def health_doc(self) -> Dict[str, Any]:
        """Router ``/healthz``: ok while any replica is ``ok``, degraded
        while anything usable remains, overloaded when the fleet is
        gone."""
        with self._rlock:
            reps = list(self._replicas.values())
        usable = [r for r in reps if r.alive and not r.straggler
                  and r.breaker.state != "open"
                  and r.state != "overloaded"]
        if any(r.state == "ok" for r in usable):
            status = "ok"
        elif usable:
            status = "degraded"
        else:
            status = "overloaded"
        with self._plock:
            inflight = len(self._pending)
        doc = {"status": status, "replicas": len(reps),
               "usable_replicas": len(usable), "inflight": inflight}
        if self._registry is not None:
            # stale-while-revalidate: how old the cached replica view is
            # (the router keeps serving it while the sync loop retries)
            age = (time.monotonic() - self._last_sync
                   if self._last_sync else -1.0)
            metrics.gauge("serving.router.replica_view_age_s").set(
                max(0.0, age))
            doc["replica_view_age_s"] = round(age, 3)
            doc["replica_view_stale"] = age > 3 * self.sync_s
            h, p = self._registry.current()
            doc["registry_endpoint"] = f"{h}:{p}"
            doc["registry_control_epoch"] = self._registry.control_epoch()
        return doc

    def fleet_snapshot(self) -> Dict[str, Any]:
        """Router-local ``/fleet`` body — the balancer's live view (the
        registry serves the authoritative one)."""
        with self._rlock:
            reps = list(self._replicas.values())
        replicas = {}
        for r in reps:
            with r.lock:
                inflight = r.inflight
                connected = r.sock is not None or r.fabric_connected
            replicas[r.jobid] = {
                "addr": r.key, "model_id": r.model_id, "health": r.state,
                "alive": r.alive, "straggler": r.straggler,
                "queue_fraction": round(r.queue_fraction, 4),
                "inflight": inflight, "connected": connected,
                "breaker": r.breaker.state,
            }
        return {"schema": "dmlc.serving.fleet/1", "ts": time.time(),
                "router": f"{self.host}:{self.port}",
                "replicas": replicas, "models": {}}

    def _rollouts_proxy(self) -> Dict[str, Any]:
        return self._registry_rpc({"cmd": "rollouts"})


def router_main(argv=None) -> int:
    """CLI: ``python -m dmlc_core_tpu.serving.fleet.router
    registry=HOST:PORT[,HOST:PORT...] [port=N] [host=0.0.0.0]`` — run a
    router against a replica registry (primary first, warm standbys
    after; ``DMLC_ROUTER_REGISTRY`` works too) until interrupted."""
    import os as _os
    import sys
    args = dict(a.split("=", 1) for a in (sys.argv[1:] if argv is None
                                          else argv))
    if ("registry" not in args and "replicas" not in args
            and not _os.environ.get("DMLC_ROUTER_REGISTRY")):
        print("usage: serving.fleet.router registry=HOST:PORT[,H:P...] "
              "[port=0] [host=0.0.0.0] | replicas=H:P,H:P,...",
              file=sys.stderr)
        return 2
    # EndpointSet parses the comma list (and env fallback) itself
    registry = args.get("registry")
    replicas = None
    if "replicas" in args:
        replicas = []
        for ep in args["replicas"].split(","):
            h, _, p = ep.rpartition(":")
            replicas.append((h, int(p)))
    router = ServingRouter(host=args.get("host", "0.0.0.0"),
                           port=int(args.get("port", "0")),
                           registry=registry, replicas=replicas)
    router.start()
    print(f"routing on {router.host}:{router.port}", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        router.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(router_main())
