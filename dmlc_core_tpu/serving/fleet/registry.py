"""Replica registry: membership, heartbeat liveness, multi-model map.

One registry process owns the serving fleet's metadata, exactly the
dispatcher's role for the ingest fleet (`pipeline/data_service/`): a
replica registers (or simply starts heartbeating — an unknown jobid's
heartbeat carrying an address IS a registration, so a replica that
outlives a registry restart re-appears on its next beat), rides its
health report and a full metrics-registry state push on every beat, and
is declared dead by the shared
:class:`~dmlc_core_tpu.parallel.tracker.LivenessBoard` rules when it
falls silent.  The state pushes feed the same tracker-side
:class:`~dmlc_core_tpu.telemetry.anomaly.StragglerBoard` the data
service uses, so the router can evict a replica that is alive but
consistently slower than its peers.

The **multi-model map** (``model_id`` → checkpoint dir → replica set)
lets one fleet serve many checkpoints: each replica names its model at
registration, ``list_replicas`` filters by model, and the canary
rollout machinery (:mod:`.rollout`) moves a model's stable checkpoint
pointer independently of every other model's.

Control flow back to replicas is **pull-based**: the registry never
dials a replica.  Directives (canary/promote/rollback hot-reloads)
queue per-jobid and ride heartbeat *replies*; the replica applies them
and acks on its next beat.  A replica behind NAT or a container bridge
needs no reachable control port.

Wire protocol: the tracker's JSON-line vocabulary (``send_json`` /
``recv_json``), one request per connection; traced requests
(``trace_id``/``parent_span`` keys) are handled under a
``serving.fleet.rpc`` span parented to the caller.

**Durability (r17).**  With a ``journal=`` prefix (or
``DMLC_REGISTRY_JOURNAL``) the registry write-ahead-journals every
durable mutation — membership, the multi-model stable-pointer map, the
per-replica directive queues, and the rollout machinery's active
canaries + ledger — through the shared
:class:`~dmlc_core_tpu.utils.durable.StateJournal` substrate, exactly
the dispatcher's pattern.  A SIGKILLed registry restarted on the same
port + journal resumes mid-rollout: the canary set, pending directive
acks, and ledger replay from disk, and replicas re-attach via the
heartbeat-is-registration idiom.  Volatile heartbeat *reports* (qps,
queue pressure, p99) are deliberately not journaled — the next beat
refreshes them.

**Fencing + warm standby.**  A journaled registry stamps a monotonic
``control_epoch`` on every reply and refreshes a
:class:`~dmlc_core_tpu.utils.durable.FencedLease` beside the journal.
A second registry started with ``standby=True`` on the same journal
serves stale reads while polling the lease; when the lease expires it
replays the journal, bumps the epoch, and takes over — after which the
old primary's writes are rejected (``fenced``) and clients'
:class:`~dmlc_core_tpu.transport.endpoints.EndpointSet` drops any
lower-epoch reply.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ...parallel.tracker import (LivenessBoard, jittered, recv_json,
                                 send_json)
from ...telemetry import flight as flight_mod
from ...telemetry import trace as teltrace
from ...telemetry.anomaly import StragglerBoard
from ...telemetry.diagnose import DiagnosisEngine
from ...telemetry.exposition import TelemetryServer
from ...telemetry.timeseries import HistoryStore
from ...transport.endpoints import EndpointSet, EndpointsLike
from ...transport.listener import Listener, serve_connection
from ...utils.durable import FencedLease, StateJournal
from ...utils.logging import DMLCError, get_logger, log_info
from ...utils.metrics import metrics
from ...utils.parameter import get_env

__all__ = ["ReplicaRegistry", "ReplicaAgent", "fleet_rpc",
           "replay_registry_state", "registry_main", "REGISTRY_SNAP_SCHEMA"]

logger = get_logger()

REGISTRY_SNAP_SCHEMA = "dmlc.fleet.registry.snapshot/1"

#: membership facts journaled per replica (the durable half of a
#: record; heartbeat report fields are volatile and live in ``_reports``)
_MEMBER_KEYS = ("host", "port", "health_port", "model_id")

#: replica report keys copied verbatim from a heartbeat into the record
_REPORT_KEYS = ("health", "queue_fraction", "queue_depth", "inflight",
                "p99_ms", "qps", "step", "params_version", "slo_breaches",
                "reload_error")


def fleet_rpc(addr: Tuple[str, int], obj: dict,
              timeout: float = 30.0) -> dict:
    """One JSON-line request/response round trip to the replica registry
    (the dispatcher_rpc idiom: trace ids ride as optional JSON keys)."""
    tid, sid = teltrace.wire_ids()
    if tid and "trace_id" not in obj:
        obj = {**obj, "trace_id": tid, "parent_span": sid}
    with socket.create_connection(addr, timeout=timeout) as s:
        s.settimeout(timeout)
        send_json(s, obj)
        reply = recv_json(s.makefile("r"))
    if reply is None:
        raise DMLCError(f"registry {addr} closed without replying "
                        f"to {obj.get('cmd')!r}")
    if "error" in reply:
        raise DMLCError(f"registry: {reply['error']}")
    return reply


def _blank_registry_state() -> Dict[str, Any]:
    return {"control_epoch": 0, "replicas": {}, "models": {},
            "directives": {},
            "rollouts": {"active": {}, "ledger": [], "seq": 0}}


def replay_registry_state(snapshot: Optional[Dict[str, Any]],
                          records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure replay of registry journal ``records`` over ``snapshot`` (or
    a blank state) — the registry mirror of the dispatcher's
    :func:`~dmlc_core_tpu.pipeline.data_service.journal.replay_state`.
    Unknown ops are skipped (forward compatibility) and records
    referencing absent replicas/rollouts are skipped too, so *any*
    prefix of a valid log replays without error — the property the HA
    tests pin.

    State shape (all JSON)::

        {"control_epoch": int,
         "replicas":   {jobid: {"host", "port", "health_port",
                                "model_id"}},
         "models":     {model_id: {"ckpt_dir", "step"}},
         "directives": {jobid: [directive, ...]},
         "rollouts":   {"active": {model_id: rollout-record},
                        "ledger": [events], "seq": int}}
    """
    state = _blank_registry_state()
    if snapshot:
        for k in ("replicas", "models", "directives", "rollouts"):
            v = snapshot.get(k)
            if isinstance(v, dict):
                state[k] = json.loads(json.dumps(v))    # deep copy
        state["control_epoch"] = int(snapshot.get("control_epoch", 0))
        state["rollouts"].setdefault("active", {})
        state["rollouts"].setdefault("ledger", [])
        state["rollouts"].setdefault("seq", 0)
    ro_tab = state["rollouts"]
    for rec in records:
        op = rec.get("op")
        if op == "epoch":
            state["control_epoch"] = max(state["control_epoch"],
                                         int(rec.get("control_epoch", 0)))
        elif op == "replica":
            state["replicas"][str(rec["jobid"])] = {
                k: rec.get(k) for k in _MEMBER_KEYS}
        elif op == "replica_gone":
            jobid = str(rec.get("jobid"))
            state["replicas"].pop(jobid, None)
            state["directives"].pop(jobid, None)
        elif op == "model":
            state["models"][str(rec["model_id"])] = {
                "ckpt_dir": rec.get("ckpt_dir"), "step": rec.get("step")}
        elif op == "directive":
            state["directives"].setdefault(str(rec["jobid"]), []) \
                .append(dict(rec.get("directive") or {}))
        elif op == "directives_drained":
            jobid = str(rec.get("jobid"))
            q = state["directives"].get(jobid) or []
            q = q[int(rec.get("count", len(q))):]
            if q:
                state["directives"][jobid] = q
            else:
                state["directives"].pop(jobid, None)
        elif op == "rollout_staged":
            ro = dict(rec.get("rollout") or {})
            if ro.get("model_id") is not None:
                ro.setdefault("acked", [])
                ro.setdefault("failed", [])
                ro_tab["active"][str(ro["model_id"])] = ro
                ro_tab["seq"] = max(int(ro_tab.get("seq", 0)),
                                    int(rec.get("seq", 0)))
        elif op == "rollout_ack":
            rid = rec.get("rollout_id")
            for ro in ro_tab["active"].values():
                if ro.get("id") != rid:
                    continue
                side = "acked" if rec.get("ok", True) else "failed"
                if rec["jobid"] not in ro[side]:
                    ro[side].append(rec["jobid"])
        elif op == "rollout_gone":
            jobid = rec.get("jobid")
            for ro in ro_tab["active"].values():
                if jobid in (ro.get("canaries") or []):
                    ro["canaries"].remove(jobid)
        elif op == "rollout_finished":
            # one fsync'd record = the atomic promote/rollback
            # transition: close the rollout AND (on promote) move the
            # stable pointer, so replay can never re-promote a closed
            # rollout or close one whose pointer move was lost
            model_id = str(rec.get("model_id"))
            ro = ro_tab["active"].get(model_id)
            if ro is not None and ro.get("id") == rec.get("rollout_id"):
                del ro_tab["active"][model_id]
                if rec.get("promoted"):
                    state["models"][model_id] = {
                        "ckpt_dir": rec.get("ckpt_dir"),
                        "step": rec.get("step")}
        elif op == "rollout_event":
            ev = rec.get("event")
            if isinstance(ev, dict):
                ro_tab["ledger"].append(ev)
    cap = 4096
    if len(ro_tab["ledger"]) > cap:
        ro_tab["ledger"] = ro_tab["ledger"][-cap:]
    return state


class ReplicaRegistry:
    """TCP control-plane server for the serving fleet.

    >>> reg = ReplicaRegistry(); reg.start()
    >>> # replicas: ReplicaAgent(server, reg.address).start()
    >>> # router:   ServingRouter(registry=reg.address)
    >>> reg.stop()

    ``heartbeat_timeout_s`` (default ``DMLC_ROUTER_HEARTBEAT_TIMEOUT``,
    5 s) declares a silent replica dead; the router drops it from the
    candidate set on its next registry sync.  ``telemetry_port`` mounts
    a :class:`TelemetryServer` with the fleet console (``/fleet``) and
    the rollout ledger (``/rollouts``) — the router usually fronts
    these instead, proxying over RPC.

    ``journal`` (default ``DMLC_REGISTRY_JOURNAL``) enables the durable
    control plane: a ``<prefix>.log``/``.snap`` journal pair plus a
    ``<prefix>.lease`` fencing lease (TTL ``DMLC_CONTROL_LEASE_S``,
    compaction threshold ``DMLC_REGISTRY_JOURNAL_SNAP_EVERY``).
    ``standby=True`` makes this instance a warm standby on the shared
    journal: reads are served from the replayed (possibly stale) state,
    writes are refused, and the instance promotes itself once the
    primary's lease expires.
    """

    #: journal-before-mutate contract, checked by the dmlclint
    #: ``durable-state`` rule: every method mutating these must journal
    _DURABLE_STATE = ("_replicas", "_models", "_directives",
                      "_control_epoch")

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 heartbeat_timeout_s: Optional[float] = None,
                 telemetry_port: Optional[int] = None,
                 journal: Optional[str] = None,
                 standby: bool = False):
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = get_env("DMLC_ROUTER_HEARTBEAT_TIMEOUT",
                                          5.0)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.liveness = LivenessBoard(self.heartbeat_timeout_s)
        self.straggler_board = StragglerBoard()
        self._lock = threading.Lock()
        #: jobid → membership record (address, model) — durable
        self._replicas: Dict[str, Dict[str, Any]] = {}
        #: jobid → latest heartbeat report fields — volatile by design
        self._reports: Dict[str, Dict[str, Any]] = {}
        #: model_id → {"ckpt_dir", "step"} — the stable pointer the
        #: rollout machinery moves on promote
        self._models: Dict[str, Dict[str, Any]] = {}
        #: jobid → queued directives, drained into heartbeat replies
        self._directives: Dict[str, List[dict]] = {}
        self._last_beat: Dict[str, float] = {}
        self._stop_ev = threading.Event()
        self._threads: List[threading.Thread] = []
        self._m_replicas = metrics.gauge("fleet.registry.replicas")
        self._listener = Listener(host, port, backlog=64)
        self._srv = self._listener.sock     # compat alias
        self.host, self.port = self._listener.host, self._listener.port
        # -- durable control plane (r17) --------------------------------
        if journal is None:
            journal = get_env("DMLC_REGISTRY_JOURNAL", "") or None
        self.standby = bool(standby)
        self._fenced = False
        self._control_epoch = 0
        self._owner = f"{self.host}:{self.port}"
        self._journal: Optional[StateJournal] = None
        self._lease: Optional[FencedLease] = None
        #: serializes journal appends against compaction; never held
        #: while taking ``_lock`` inside an append path (``_jlog`` is
        #: always called with no registry/rollout lock held)
        self._jmutex = threading.Lock()
        self._journal_snap_every = max(16, int(get_env(
            "DMLC_REGISTRY_JOURNAL_SNAP_EVERY", 512)))
        restored: Optional[Dict[str, Any]] = None
        if journal:
            self._journal = StateJournal(
                str(journal), snap_schema=REGISTRY_SNAP_SCHEMA,
                on_append=metrics.counter(
                    "fleet.registry.journal.appends").add,
                on_snapshot=metrics.counter(
                    "fleet.registry.journal.snapshots").add)
            self._lease = FencedLease(
                str(journal) + ".lease",
                ttl_s=float(get_env("DMLC_CONTROL_LEASE_S", 2.0)))
            with self._lock:
                restored = self._restore_locked()
        from .rollout import RolloutManager
        self.rollouts = RolloutManager(self)
        if restored is not None:
            self.rollouts._restore_state(restored.get("rollouts") or {})
        if self._journal is not None and not self.standby:
            self._become_primary()
        # fleet timeline: the registry's own counters plus synthetic
        # fleet-level gauges derived from heartbeat reports, so
        # /timeline answers "how did alive-count / aggregate inflight /
        # worst queue pressure move" without scraping every replica
        self.history = HistoryStore(snapshot_fn=self._history_snapshot)
        self.telemetry: Optional[TelemetryServer] = None
        if telemetry_port is not None:
            # /diagnose over the MERGED fleet view: the registry's
            # synthetic fleet gauges, the per-model straggler board,
            # and the replica console rows
            self.telemetry = TelemetryServer(
                port=int(telemetry_port),
                fleet_fn=self.fleet_snapshot,
                rollouts_fn=self.rollouts.snapshot,
                timeline_fn=self.history.timeline,
                diagnose_fn=DiagnosisEngine(
                    history=self.history,
                    stragglers_fn=self.straggler_board.snapshot,
                    fleet_fn=self.fleet_snapshot,
                ).endpoint_doc)

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    # -- durable control plane (r17) -------------------------------------
    def _jlog(self, op: str, **fields: Any) -> None:
        """One write-ahead journal record; no-op without a journal.
        Callers must not hold ``_lock`` or the rollout lock (compaction
        takes ``_jmutex`` first, then those — same order everywhere)."""
        if self._journal is None:
            return
        with self._jmutex:
            self._journal.append({"op": op, "ts": time.time(), **fields})

    def _durable_state_locked(self) -> Dict[str, Any]:
        return {
            "control_epoch": self._control_epoch,
            "replicas": {j: {k: r.get(k) for k in _MEMBER_KEYS}
                         for j, r in self._replicas.items()},
            "models": {m: dict(ptr) for m, ptr in self._models.items()},
            "directives": {j: [dict(d) for d in q]
                           for j, q in self._directives.items() if q},
        }

    def _restore_locked(self) -> Optional[Dict[str, Any]]:
        """Replay the journal into the membership / model / directive
        tables; returns the full replayed state (the rollout slice is
        applied by the caller once the RolloutManager exists)."""
        self._replicas.clear()
        self._models.clear()
        self._directives.clear()
        snap, records = self._journal.load()
        if snap is None and not records:
            return None
        state = replay_registry_state(snap, records)
        self._control_epoch = int(state.get("control_epoch", 0))
        self._replicas = {j: {k: r.get(k) for k in _MEMBER_KEYS}
                          for j, r in state.get("replicas", {}).items()}
        self._models = {m: dict(p)
                        for m, p in state.get("models", {}).items()}
        self._directives = {j: [dict(d) for d in q]
                            for j, q in state.get("directives", {}).items()
                            if q}
        now = time.monotonic()
        for jobid in self._replicas:
            # liveness grace: a restored replica gets a full heartbeat
            # window to re-attach before the sweep declares it dead
            self.liveness.beat(jobid)
            self._last_beat[jobid] = now
        self._m_replicas.set(len(self._replicas))
        metrics.counter("fleet.registry.journal.replayed") \
            .add(len(records))
        log_info("fleet registry: replayed %d journal record(s) over "
                 "%s snapshot → %d replica(s), %d model(s), epoch %d",
                 len(records), "a" if snap else "no",
                 len(self._replicas), len(self._models),
                 self._control_epoch)
        return state

    def _become_primary(self) -> None:
        """Claim (or re-claim) the fencing lease: bump the monotonic
        ``control_epoch`` past anything the journal or lease has seen,
        journal it, stamp the lease, and compact."""
        lease_epoch = self._lease.current_epoch() if self._lease else 0
        epoch = max(self._control_epoch, lease_epoch) + 1
        self._jlog("epoch", control_epoch=epoch)
        with self._lock:
            self._control_epoch = epoch
        self._fenced = False
        if self._lease is not None:
            self._lease.refresh(self._owner, epoch)
        metrics.gauge("fleet.registry.control_epoch").set(epoch)
        self._compact()
        log_info("fleet registry %s: primary at control_epoch %d",
                 self._owner, epoch)

    def _compact(self) -> None:
        if self._journal is None:
            return
        with self._jmutex:
            with self._lock:
                state = self._durable_state_locked()
            state["rollouts"] = self.rollouts.durable_snapshot()
            self._journal.compact(state)

    def _fence_error(self) -> Optional[dict]:
        """Reject writes once a standby has taken over: the on-disk
        lease carrying a higher epoch than ours means we are the stale
        primary.  Standbys refuse writes outright until promotion."""
        if self._journal is None:
            return None
        if self.standby:
            return {"error": "standby: not primary (reads only)",
                    "control_epoch": self._control_epoch}
        if not self._fenced and self._lease is not None:
            if self._lease.current_epoch() > self._control_epoch:
                self._fenced = True
        if self._fenced:
            metrics.counter("fleet.registry.fenced").add(1)
            return {"error": f"fenced: control_epoch "
                             f"{self._control_epoch} superseded",
                    "control_epoch": self._control_epoch}
        return None

    def _standby_loop(self) -> None:
        """Warm standby: poll the primary's lease; replay + take over
        once it expires."""
        poll = max(0.05, (self._lease.ttl_s if self._lease else 2.0) / 4.0)
        while not self._stop_ev.wait(jittered(poll)):
            if self._lease is None or not self._lease.expired():
                continue
            metrics.counter("fleet.registry.takeovers").add(1)
            log_info("fleet registry %s: primary lease expired — "
                     "taking over", self._owner)
            with self._lock:
                restored = self._restore_locked()
            self.rollouts._restore_state(
                (restored or {}).get("rollouts") or {})
            self.standby = False
            self._become_primary()
            self._sweep_loop()
            return

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaRegistry":
        sweep = self._standby_loop if self.standby else self._sweep_loop
        self._threads.append(self._listener.spawn(
            self._on_conn, name="fleet-registry-accept",
            stopping=self._stop_ev.is_set))
        t = threading.Thread(target=sweep, name="fleet-registry-sweep",
                             daemon=True)
        t.start()
        self._threads.append(t)
        self.rollouts.start()
        if self.telemetry is not None:
            self.telemetry.start()
            self.history.start()
        # incident bundles dumped in this process carry the rollout
        # ledger — a bad-canary postmortem reads transitions directly
        flight_mod.register_contributor("rollout_ledger",
                                        self.rollouts.snapshot)
        log_info("serving fleet registry on %s:%d (heartbeat timeout "
                 "%.1fs)", self.host, self.port, self.heartbeat_timeout_s)
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        flight_mod.unregister_contributor("rollout_ledger")
        self.history.stop()
        self.rollouts.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
        # Listener.close() is shutdown()-before-close(): close() alone
        # does not wake a thread blocked inside accept()
        self._listener.close()
        for t in self._threads:
            t.join(timeout=5.0)
        if self._journal is not None:
            if not self.standby and not self._fenced:
                self._compact()         # clean stop: snapshot + empty log
            self._journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- introspection ---------------------------------------------------
    def replica_records(self, model_id: Optional[str] = None
                        ) -> Dict[str, Dict[str, Any]]:
        """jobid → record copy (with ``alive``/``straggler`` flags) —
        the rollout manager's and ``list_replicas``'s shared view."""
        try:
            suspects = set(self.straggler_board.suspects())
        except Exception:   # <3 replicas / no pushes yet — board is moot
            suspects = set()
        dead = self.liveness.dead_members()
        with self._lock:
            out = {}
            for jobid, rec in self._replicas.items():
                if model_id is not None and rec.get("model_id") != model_id:
                    continue
                out[jobid] = {**rec, **self._reports.get(jobid, {}),
                              "alive": jobid not in dead,
                              "straggler": jobid in suspects}
            return out

    def models_snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            by_model: Dict[str, List[str]] = {}
            for jobid, rec in self._replicas.items():
                by_model.setdefault(str(rec.get("model_id")), []) \
                    .append(jobid)
            return {m: {**ptr, "replicas": sorted(by_model.get(m, []))}
                    for m, ptr in self._models.items()} | {
                m: {"ckpt_dir": None, "step": None, "replicas": sorted(js)}
                for m, js in by_model.items() if m not in self._models}

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The ``/fleet`` body: per-replica health / load / heartbeat age
        / straggler flags plus the multi-model map."""
        now = time.monotonic()
        records = self.replica_records()
        with self._lock:
            beats = dict(self._last_beat)
        replicas = {}
        for jobid, rec in records.items():
            beat = beats.get(jobid)
            replicas[jobid] = {
                "addr": f"{rec.get('host')}:{rec.get('port')}",
                "model_id": rec.get("model_id"),
                "health": rec.get("health", "?"),
                "alive": rec.get("alive", True),
                "straggler": rec.get("straggler", False),
                "heartbeat_age_s": (round(now - beat, 3)
                                    if beat is not None else None),
                "queue_fraction": rec.get("queue_fraction", 0.0),
                "inflight": rec.get("inflight", 0),
                "qps": rec.get("qps", 0.0),
                "p99_ms": rec.get("p99_ms"),
                "step": rec.get("step"),
            }
        return {"schema": "dmlc.serving.fleet/1", "ts": time.time(),
                "heartbeat_timeout_s": self.heartbeat_timeout_s,
                "replicas": replicas, "models": self.models_snapshot()}

    def _history_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """What the fleet timeline samples: the registry's own registry
        plus snapshot-form gauges rolled up from replica heartbeats."""
        records = self.replica_records()
        alive = [r for r in records.values() if r.get("alive")]
        rollup = {
            "fleet.replicas.alive": float(len(alive)),
            "fleet.replicas.total": float(len(records)),
            "fleet.inflight.total": float(sum(
                r.get("inflight") or 0 for r in alive)),
            "fleet.qps.total": float(sum(r.get("qps") or 0.0
                                         for r in alive)),
            "fleet.queue_fraction.max": float(max(
                (r.get("queue_fraction") or 0.0 for r in alive),
                default=0.0)),
        }
        snap = dict(metrics.snapshot())
        for name, v in rollup.items():
            snap[name] = {"type": "gauge", "value": v}
        return snap

    # -- rollout plumbing ------------------------------------------------
    def push_directive(self, jobid: str, directive: dict) -> None:
        """Queue a directive for a replica's next heartbeat reply."""
        self._jlog("directive", jobid=jobid, directive=directive)
        with self._lock:
            self._directives.setdefault(jobid, []).append(directive)

    def stable_pointer(self, model_id: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._models.get(model_id) or {})

    def set_stable_pointer(self, model_id: str, ckpt_dir: Optional[str],
                           step: Optional[int]) -> None:
        self._jlog("model", model_id=model_id, ckpt_dir=ckpt_dir,
                   step=step)
        with self._lock:
            self._models[model_id] = {"ckpt_dir": ckpt_dir, "step": step}

    # -- liveness --------------------------------------------------------
    def _beat(self, jobid: str) -> None:
        self.liveness.beat(jobid)
        with self._lock:
            self._last_beat[jobid] = time.monotonic()

    def _sweep_loop(self) -> None:
        interval = max(0.05, self.heartbeat_timeout_s / 4.0)
        if self._lease is not None:
            interval = min(interval, max(0.05, self._lease.ttl_s / 3.0))
        while not self._stop_ev.wait(interval):
            for jobid, silence in self.liveness.sweep():
                metrics.counter("fleet.registry.dead_replicas").add(1)
                logger.warning("fleet registry: replica %r silent for "
                               "%.1fs — declaring dead", jobid, silence)
            if self._lease is not None and not self._fenced:
                if not self._lease.refresh(self._owner,
                                           self._control_epoch):
                    self._fenced = True
                    logger.warning("fleet registry %s: fenced by a "
                                   "standby takeover (epoch %d "
                                   "superseded) — refusing writes",
                                   self._owner, self._control_epoch)
            if (self._journal is not None
                    and self._journal.appends_since_snapshot
                    >= self._journal_snap_every):
                self._compact()

    # -- request handling ------------------------------------------------
    def _on_conn(self, conn: socket.socket, _addr) -> None:
        serve_connection(self._handle, conn, name="fleet-registry-rpc")

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30.0)
            msg = recv_json(conn.makefile("r"))
            if msg is None:
                return
            ctx = teltrace.from_wire(msg.get("trace_id"),
                                     msg.get("parent_span"))
            if ctx is not None:
                with teltrace.activate(ctx), \
                        teltrace.span("serving.fleet.rpc",
                                      cmd=msg.get("cmd")):
                    reply = self._dispatch(msg)
            else:
                reply = self._dispatch(msg)
            send_json(conn, reply)
        except (OSError, ValueError, KeyError, TypeError) as e:
            logger.warning("fleet registry connection error: %s", e)
            try:
                send_json(conn, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    #: commands that mutate durable state — fenced once a standby takes
    #: over (reads keep flowing from a stale primary; writes must not)
    _WRITE_CMDS = frozenset({"register_replica", "deregister_replica",
                             "heartbeat", "set_model", "stage_rollout"})

    def _dispatch(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd in self._WRITE_CMDS:
            fenced = self._fence_error()
            if fenced is not None:
                return fenced
        reply = self._dispatch_cmd(msg)
        if isinstance(reply, dict):
            # every reply carries the fencing epoch: EndpointSet drops
            # replies stamped lower than the highest it has seen
            reply.setdefault("control_epoch", self._control_epoch)
        return reply

    def _dispatch_cmd(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd == "register_replica":
            return self._cmd_register(msg)
        if cmd == "deregister_replica":
            return self._cmd_deregister(msg)
        if cmd == "heartbeat":
            return self._cmd_heartbeat(msg)
        if cmd == "list_replicas":
            model = msg.get("model_id")
            recs = self.replica_records(model)
            return {"replicas": [
                {"jobid": j, "host": r.get("host"), "port": r.get("port"),
                 "health_port": r.get("health_port"),
                 "model_id": r.get("model_id"),
                 "health": r.get("health", "ok"),
                 "queue_fraction": r.get("queue_fraction", 0.0),
                 "inflight": r.get("inflight", 0),
                 "alive": r.get("alive", True),
                 "straggler": r.get("straggler", False),
                 "step": r.get("step")}
                for j, r in sorted(recs.items())]}
        if cmd == "set_model":
            self.set_stable_pointer(str(msg["model_id"]),
                                    msg.get("ckpt_dir"), msg.get("step"))
            return {"ok": True}
        if cmd == "models":
            return {"models": self.models_snapshot()}
        if cmd == "fleet":
            return self.fleet_snapshot()
        if cmd == "stage_rollout":
            return self.rollouts.stage(
                str(msg["model_id"]), str(msg["ckpt_dir"]),
                step=msg.get("step"), fraction=msg.get("fraction"),
                bake_s=msg.get("bake_s"))
        if cmd == "rollouts":
            return self.rollouts.snapshot()
        return {"error": f"unknown cmd {cmd!r}"}

    def _register(self, msg: dict) -> None:
        jobid = str(msg["jobid"])
        rec = {"host": str(msg["host"]), "port": int(msg["port"]),
               "health_port": msg.get("health_port"),
               "model_id": str(msg.get("model_id") or "default")}
        self._jlog("replica", jobid=jobid, **rec)
        with self._lock:
            self._replicas.setdefault(jobid, {}).update(rec)
            self._m_replicas.set(len(self._replicas))
        self._beat(jobid)

    def _cmd_register(self, msg: dict) -> dict:
        self._register(msg)
        log_info("fleet registry: replica %r registered at %s:%s "
                 "(model=%s)", msg["jobid"], msg["host"], msg["port"],
                 msg.get("model_id") or "default")
        return {"ok": True}

    def _cmd_deregister(self, msg: dict) -> dict:
        jobid = str(msg["jobid"])
        self._jlog("replica_gone", jobid=jobid)
        with self._lock:
            self._replicas.pop(jobid, None)
            self._reports.pop(jobid, None)
            self._directives.pop(jobid, None)
            self._last_beat.pop(jobid, None)
            self._m_replicas.set(len(self._replicas))
        self.liveness.forget(jobid)
        self.rollouts.on_replica_gone(jobid)
        return {"ok": True}

    def _cmd_heartbeat(self, msg: dict) -> dict:
        jobid = str(msg["jobid"])
        with self._lock:
            known = jobid in self._replicas
        if not known and "host" in msg and "port" in msg:
            # auto-registration: the first beat after a registry restart
            # (or a replica that skipped explicit registration) carries
            # its address — a heartbeat IS a registration
            self._register(msg)
            log_info("fleet registry: replica %r auto-registered via "
                     "heartbeat", jobid)
        self._beat(jobid)
        report = {k: msg[k] for k in _REPORT_KEYS if k in msg}
        with self._lock:
            if jobid in self._replicas:
                self._reports.setdefault(jobid, {}).update(report)
            directives = self._directives.pop(jobid, [])
        if directives:
            # journaled *after* the pop: a crash in between replays the
            # directives (at-least-once — reloads are idempotent and
            # acks dedup), never loses them.  count-based so a push
            # racing this drain keeps its queue position on replay.
            self._jlog("directives_drained", jobid=jobid,
                       count=len(directives))
        state = msg.get("state")
        if isinstance(state, dict):
            # metric push riding the heartbeat: feeds cross-replica
            # straggler detection, same as the data-service fleet
            self.straggler_board.update(jobid, state)
        for ack in msg.get("applied") or []:
            self.rollouts.on_ack(jobid, ack)
        return {"ok": True, "directives": directives}


class ReplicaAgent:
    """The replica-side half of the control plane, run inside a
    :class:`~dmlc_core_tpu.serving.server.PredictionServer` process.

    Registers the replica, then heartbeats at ``DMLC_ROUTER_HEARTBEAT``
    cadence carrying the live ``/healthz`` body (health word,
    queue-depth fraction, inflight), serving p99, checkpoint step and a
    full metrics-state push; applies hot-reload directives carried in
    heartbeat replies and acks them on the next beat.  A dead registry
    never takes the replica down: failed beats log at most once per
    outage and the loop keeps probing (the next successful beat
    re-registers via the heartbeat auto-registration path).

    ``report_overrides`` lets tests and operators force report fields
    (e.g. ``{"slo_breaches": 1}`` to drill the canary auto-rollback).

    ``registry_addr`` accepts a single ``(host, port)`` tuple, a
    ``"host:port,host:port"`` string, or a list of either: beats walk
    the :class:`~dmlc_core_tpu.transport.endpoints.EndpointSet` in
    sticky order, so a standby registry picks up the fleet's heartbeats
    the moment it takes over (r17).
    """

    def __init__(self, server: Any, registry_addr: EndpointsLike, *,
                 jobid: Optional[str] = None,
                 model_id: Optional[str] = None,
                 interval_s: Optional[float] = None):
        self.server = server
        self.registry = EndpointSet(registry_addr,
                                    env_prefix="DMLC_ROUTER",
                                    name="fleet.agent")
        self.registry_addr = self.registry.primary
        self.jobid = jobid or f"replica-{server.host}:{server.port}"
        self.model_id = (model_id or getattr(server, "model_id", None)
                         or "default")
        if interval_s is None:
            interval_s = get_env("DMLC_ROUTER_HEARTBEAT", 1.0)
        self.interval_s = max(0.05, float(interval_s))
        self.report_overrides: Dict[str, Any] = {}
        self._acks: List[dict] = []
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry_down = False

    # -- report assembly -------------------------------------------------
    def _report(self) -> Dict[str, Any]:
        doc = self.server.health_doc() if hasattr(self.server,
                                                  "health_doc") else {}
        snap = metrics.snapshot()
        lat = snap.get("serving.latency_s") or {}
        reqs = snap.get("serving.batcher.requests") or {}
        engine = getattr(self.server, "engine", None)
        report: Dict[str, Any] = {
            "jobid": self.jobid, "host": self.server.host,
            "port": self.server.port, "model_id": self.model_id,
            "health": doc.get("status", "ok"),
            "queue_fraction": doc.get("queue_fraction", 0.0),
            "queue_depth": doc.get("queue_depth", 0),
            "inflight": doc.get("inflight", 0),
            "p99_ms": float(lat.get("p99", 0.0) or 0.0) * 1e3,
            "qps": float(reqs.get("windowed_rate",
                                  reqs.get("rate", 0.0)) or 0.0),
            "step": getattr(self, "_step", None),
            "params_version": getattr(engine, "params_version", None),
            "slo_breaches": int(
                metrics.gauge("slo.active_breaches").value),
            "state": snap,
        }
        telemetry = getattr(self.server, "telemetry", None)
        if telemetry is not None:
            report["health_port"] = telemetry.port
        report.update(self.report_overrides)
        return report

    def _apply(self, directive: dict) -> None:
        kind = directive.get("kind")
        ack = {"rollout_id": directive.get("rollout_id"), "kind": kind}
        if kind == "reload":
            try:
                step = self.server.reload_from_checkpoint(
                    str(directive["ckpt_dir"]), directive.get("step"))
                self._step = step
                ack.update(ok=True, step=step)
            except Exception as e:  # noqa: BLE001 — a bad checkpoint must
                # not kill the replica; the registry learns via the ack
                ack.update(ok=False, error=str(e))
                logger.warning("fleet agent %s: reload directive failed: "
                               "%s", self.jobid, e)
        else:
            ack.update(ok=False, error=f"unknown directive {kind!r}")
        with self._lock:
            self._acks.append(ack)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaAgent":
        try:
            self.registry.call(lambda addr: fleet_rpc(
                addr, {"cmd": "register_replica", **self._report()},
                timeout=5.0))
        except (OSError, DMLCError) as e:
            # heartbeat auto-registration picks this up once the
            # registry is reachable
            logger.warning("fleet agent %s: registration deferred (%s)",
                           self.jobid, e)
        self._thread = threading.Thread(target=self._run,
                                        name=f"fleet-agent-{self.jobid}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        try:
            self.registry.call(lambda addr: fleet_rpc(
                addr, {"cmd": "deregister_replica", "jobid": self.jobid},
                timeout=2.0))
        except (OSError, DMLCError):
            pass               # registry gone — its sweep will notice

    def _run(self) -> None:
        # jittered beats (±DMLC_HEARTBEAT_JITTER): a restarted registry
        # must not absorb every agent's re-registration in one instant
        while not self._stop_ev.wait(jittered(self.interval_s)):
            msg = {"cmd": "heartbeat", **self._report()}
            with self._lock:
                if self._acks:
                    msg["applied"], self._acks = self._acks, []
            try:
                reply = self.registry.call(
                    lambda addr: fleet_rpc(addr, msg, timeout=5.0))
            except (OSError, DMLCError) as e:
                if not self._registry_down:
                    self._registry_down = True
                    logger.warning("fleet agent %s: heartbeat failed "
                                   "(%s) — will keep probing", self.jobid, e)
                with self._lock:
                    # re-queue unacked directives' acks for the next beat
                    self._acks = msg.get("applied", []) + self._acks
                continue
            self._registry_down = False
            for directive in reply.get("directives") or []:
                self._apply(directive)


def registry_main(argv=None) -> int:
    """CLI: ``python -m dmlc_core_tpu.serving.fleet.registry [host=H]
    [port=N] [journal=PREFIX] [standby=1] [heartbeat_timeout=S]`` —
    serve until killed.

    The chaos-drill surface, mirroring ``dispatcher_main``: the HA
    tests run the registry as a subprocess, SIGKILL it mid-rollout, and
    restart it (or promote a standby) on the same ``journal=`` to prove
    the replay resumes the canary.  The bound port is printed as one
    JSON line on stdout (``{"host": ..., "port": ...}``); SIGTERM is a
    clean stop (journal compacted), SIGKILL is the crash the journal
    exists for."""
    import signal
    import sys
    args = list(sys.argv[1:] if argv is None else argv)
    kw = dict(a.split("=", 1) for a in args)
    reg = ReplicaRegistry(
        host=kw.get("host", "127.0.0.1"),
        port=int(kw.get("port", 0)),
        journal=kw.get("journal") or None,
        standby=kw.get("standby", "") not in ("", "0", "false"),
        heartbeat_timeout_s=(float(kw["heartbeat_timeout"])
                             if "heartbeat_timeout" in kw else None))
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda signum, frame: done.set())
    reg.start()
    print(json.dumps({"host": reg.host, "port": reg.port}), flush=True)
    try:
        while not done.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    reg.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(registry_main())
