"""Replica registry: membership, heartbeat liveness, multi-model map.

One registry process owns the serving fleet's metadata, exactly the
dispatcher's role for the ingest fleet (`pipeline/data_service/`): a
replica registers (or simply starts heartbeating — an unknown jobid's
heartbeat carrying an address IS a registration, so a replica that
outlives a registry restart re-appears on its next beat), rides its
health report and a full metrics-registry state push on every beat, and
is declared dead by the shared
:class:`~dmlc_core_tpu.parallel.tracker.LivenessBoard` rules when it
falls silent.  The state pushes feed the same tracker-side
:class:`~dmlc_core_tpu.telemetry.anomaly.StragglerBoard` the data
service uses, so the router can evict a replica that is alive but
consistently slower than its peers.

The **multi-model map** (``model_id`` → checkpoint dir → replica set)
lets one fleet serve many checkpoints: each replica names its model at
registration, ``list_replicas`` filters by model, and the canary
rollout machinery (:mod:`.rollout`) moves a model's stable checkpoint
pointer independently of every other model's.

Control flow back to replicas is **pull-based**: the registry never
dials a replica.  Directives (canary/promote/rollback hot-reloads)
queue per-jobid and ride heartbeat *replies*; the replica applies them
and acks on its next beat.  A replica behind NAT or a container bridge
needs no reachable control port.

Wire protocol: the tracker's JSON-line vocabulary (``send_json`` /
``recv_json``), one request per connection; traced requests
(``trace_id``/``parent_span`` keys) are handled under a
``serving.fleet.rpc`` span parented to the caller.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ...parallel.tracker import (LivenessBoard, jittered, recv_json,
                                 send_json)
from ...telemetry import flight as flight_mod
from ...telemetry import trace as teltrace
from ...telemetry.anomaly import StragglerBoard
from ...telemetry.exposition import TelemetryServer
from ...telemetry.timeseries import HistoryStore
from ...utils.logging import DMLCError, get_logger, log_info
from ...utils.metrics import metrics
from ...utils.parameter import get_env

__all__ = ["ReplicaRegistry", "ReplicaAgent", "fleet_rpc"]

logger = get_logger()

#: replica report keys copied verbatim from a heartbeat into the record
_REPORT_KEYS = ("health", "queue_fraction", "queue_depth", "inflight",
                "p99_ms", "qps", "step", "params_version", "slo_breaches",
                "reload_error")


def fleet_rpc(addr: Tuple[str, int], obj: dict,
              timeout: float = 30.0) -> dict:
    """One JSON-line request/response round trip to the replica registry
    (the dispatcher_rpc idiom: trace ids ride as optional JSON keys)."""
    tid, sid = teltrace.wire_ids()
    if tid and "trace_id" not in obj:
        obj = {**obj, "trace_id": tid, "parent_span": sid}
    with socket.create_connection(addr, timeout=timeout) as s:
        s.settimeout(timeout)
        send_json(s, obj)
        reply = recv_json(s.makefile("r"))
    if reply is None:
        raise DMLCError(f"registry {addr} closed without replying "
                        f"to {obj.get('cmd')!r}")
    if "error" in reply:
        raise DMLCError(f"registry: {reply['error']}")
    return reply


class ReplicaRegistry:
    """TCP control-plane server for the serving fleet.

    >>> reg = ReplicaRegistry(); reg.start()
    >>> # replicas: ReplicaAgent(server, reg.address).start()
    >>> # router:   ServingRouter(registry=reg.address)
    >>> reg.stop()

    ``heartbeat_timeout_s`` (default ``DMLC_ROUTER_HEARTBEAT_TIMEOUT``,
    5 s) declares a silent replica dead; the router drops it from the
    candidate set on its next registry sync.  ``telemetry_port`` mounts
    a :class:`TelemetryServer` with the fleet console (``/fleet``) and
    the rollout ledger (``/rollouts``) — the router usually fronts
    these instead, proxying over RPC.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 heartbeat_timeout_s: Optional[float] = None,
                 telemetry_port: Optional[int] = None):
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = get_env("DMLC_ROUTER_HEARTBEAT_TIMEOUT",
                                          5.0)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.liveness = LivenessBoard(self.heartbeat_timeout_s)
        self.straggler_board = StragglerBoard()
        self._lock = threading.Lock()
        #: jobid → replica record (address + latest heartbeat report)
        self._replicas: Dict[str, Dict[str, Any]] = {}
        #: model_id → {"ckpt_dir", "step"} — the stable pointer the
        #: rollout machinery moves on promote
        self._models: Dict[str, Dict[str, Any]] = {}
        #: jobid → queued directives, drained into heartbeat replies
        self._directives: Dict[str, List[dict]] = {}
        self._last_beat: Dict[str, float] = {}
        self._stop_ev = threading.Event()
        self._threads: List[threading.Thread] = []
        self._m_replicas = metrics.gauge("fleet.registry.replicas")
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()[:2]
        from .rollout import RolloutManager
        self.rollouts = RolloutManager(self)
        # fleet timeline: the registry's own counters plus synthetic
        # fleet-level gauges derived from heartbeat reports, so
        # /timeline answers "how did alive-count / aggregate inflight /
        # worst queue pressure move" without scraping every replica
        self.history = HistoryStore(snapshot_fn=self._history_snapshot)
        self.telemetry: Optional[TelemetryServer] = None
        if telemetry_port is not None:
            self.telemetry = TelemetryServer(
                port=int(telemetry_port),
                fleet_fn=self.fleet_snapshot,
                rollouts_fn=self.rollouts.snapshot,
                timeline_fn=self.history.timeline)

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaRegistry":
        for target, name in ((self._accept_loop, "fleet-registry-accept"),
                             (self._sweep_loop, "fleet-registry-sweep")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self.rollouts.start()
        if self.telemetry is not None:
            self.telemetry.start()
            self.history.start()
        # incident bundles dumped in this process carry the rollout
        # ledger — a bad-canary postmortem reads transitions directly
        flight_mod.register_contributor("rollout_ledger",
                                        self.rollouts.snapshot)
        log_info("serving fleet registry on %s:%d (heartbeat timeout "
                 "%.1fs)", self.host, self.port, self.heartbeat_timeout_s)
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        flight_mod.unregister_contributor("rollout_ledger")
        self.history.stop()
        self.rollouts.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
        # shutdown() before close(): close() alone does not wake a thread
        # blocked inside accept() (see PredictionServer.stop)
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- introspection ---------------------------------------------------
    def replica_records(self, model_id: Optional[str] = None
                        ) -> Dict[str, Dict[str, Any]]:
        """jobid → record copy (with ``alive``/``straggler`` flags) —
        the rollout manager's and ``list_replicas``'s shared view."""
        try:
            suspects = set(self.straggler_board.suspects())
        except Exception:   # <3 replicas / no pushes yet — board is moot
            suspects = set()
        dead = self.liveness.dead_members()
        with self._lock:
            out = {}
            for jobid, rec in self._replicas.items():
                if model_id is not None and rec.get("model_id") != model_id:
                    continue
                out[jobid] = {**rec, "alive": jobid not in dead,
                              "straggler": jobid in suspects}
            return out

    def models_snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            by_model: Dict[str, List[str]] = {}
            for jobid, rec in self._replicas.items():
                by_model.setdefault(str(rec.get("model_id")), []) \
                    .append(jobid)
            return {m: {**ptr, "replicas": sorted(by_model.get(m, []))}
                    for m, ptr in self._models.items()} | {
                m: {"ckpt_dir": None, "step": None, "replicas": sorted(js)}
                for m, js in by_model.items() if m not in self._models}

    def fleet_snapshot(self) -> Dict[str, Any]:
        """The ``/fleet`` body: per-replica health / load / heartbeat age
        / straggler flags plus the multi-model map."""
        now = time.monotonic()
        records = self.replica_records()
        with self._lock:
            beats = dict(self._last_beat)
        replicas = {}
        for jobid, rec in records.items():
            beat = beats.get(jobid)
            replicas[jobid] = {
                "addr": f"{rec.get('host')}:{rec.get('port')}",
                "model_id": rec.get("model_id"),
                "health": rec.get("health", "?"),
                "alive": rec.get("alive", True),
                "straggler": rec.get("straggler", False),
                "heartbeat_age_s": (round(now - beat, 3)
                                    if beat is not None else None),
                "queue_fraction": rec.get("queue_fraction", 0.0),
                "inflight": rec.get("inflight", 0),
                "qps": rec.get("qps", 0.0),
                "p99_ms": rec.get("p99_ms"),
                "step": rec.get("step"),
            }
        return {"schema": "dmlc.serving.fleet/1", "ts": time.time(),
                "heartbeat_timeout_s": self.heartbeat_timeout_s,
                "replicas": replicas, "models": self.models_snapshot()}

    def _history_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """What the fleet timeline samples: the registry's own registry
        plus snapshot-form gauges rolled up from replica heartbeats."""
        records = self.replica_records()
        alive = [r for r in records.values() if r.get("alive")]
        rollup = {
            "fleet.replicas.alive": float(len(alive)),
            "fleet.replicas.total": float(len(records)),
            "fleet.inflight.total": float(sum(
                r.get("inflight") or 0 for r in alive)),
            "fleet.qps.total": float(sum(r.get("qps") or 0.0
                                         for r in alive)),
            "fleet.queue_fraction.max": float(max(
                (r.get("queue_fraction") or 0.0 for r in alive),
                default=0.0)),
        }
        snap = dict(metrics.snapshot())
        for name, v in rollup.items():
            snap[name] = {"type": "gauge", "value": v}
        return snap

    # -- rollout plumbing ------------------------------------------------
    def push_directive(self, jobid: str, directive: dict) -> None:
        """Queue a directive for a replica's next heartbeat reply."""
        with self._lock:
            self._directives.setdefault(jobid, []).append(directive)

    def stable_pointer(self, model_id: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._models.get(model_id) or {})

    def set_stable_pointer(self, model_id: str, ckpt_dir: Optional[str],
                           step: Optional[int]) -> None:
        with self._lock:
            self._models[model_id] = {"ckpt_dir": ckpt_dir, "step": step}

    # -- liveness --------------------------------------------------------
    def _beat(self, jobid: str) -> None:
        self.liveness.beat(jobid)
        with self._lock:
            self._last_beat[jobid] = time.monotonic()

    def _sweep_loop(self) -> None:
        interval = max(0.05, self.heartbeat_timeout_s / 4.0)
        while not self._stop_ev.wait(interval):
            for jobid, silence in self.liveness.sweep():
                metrics.counter("fleet.registry.dead_replicas").add(1)
                logger.warning("fleet registry: replica %r silent for "
                               "%.1fs — declaring dead", jobid, silence)

    # -- request handling ------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop_ev.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             name="fleet-registry-rpc",
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(30.0)
            msg = recv_json(conn.makefile("r"))
            if msg is None:
                return
            ctx = teltrace.from_wire(msg.get("trace_id"),
                                     msg.get("parent_span"))
            if ctx is not None:
                with teltrace.activate(ctx), \
                        teltrace.span("serving.fleet.rpc",
                                      cmd=msg.get("cmd")):
                    reply = self._dispatch(msg)
            else:
                reply = self._dispatch(msg)
            send_json(conn, reply)
        except (OSError, ValueError, KeyError, TypeError) as e:
            logger.warning("fleet registry connection error: %s", e)
            try:
                send_json(conn, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd == "register_replica":
            return self._cmd_register(msg)
        if cmd == "deregister_replica":
            return self._cmd_deregister(msg)
        if cmd == "heartbeat":
            return self._cmd_heartbeat(msg)
        if cmd == "list_replicas":
            model = msg.get("model_id")
            recs = self.replica_records(model)
            return {"replicas": [
                {"jobid": j, "host": r.get("host"), "port": r.get("port"),
                 "health_port": r.get("health_port"),
                 "model_id": r.get("model_id"),
                 "health": r.get("health", "ok"),
                 "queue_fraction": r.get("queue_fraction", 0.0),
                 "inflight": r.get("inflight", 0),
                 "alive": r.get("alive", True),
                 "straggler": r.get("straggler", False),
                 "step": r.get("step")}
                for j, r in sorted(recs.items())]}
        if cmd == "set_model":
            self.set_stable_pointer(str(msg["model_id"]),
                                    msg.get("ckpt_dir"), msg.get("step"))
            return {"ok": True}
        if cmd == "models":
            return {"models": self.models_snapshot()}
        if cmd == "fleet":
            return self.fleet_snapshot()
        if cmd == "stage_rollout":
            return self.rollouts.stage(
                str(msg["model_id"]), str(msg["ckpt_dir"]),
                step=msg.get("step"), fraction=msg.get("fraction"),
                bake_s=msg.get("bake_s"))
        if cmd == "rollouts":
            return self.rollouts.snapshot()
        return {"error": f"unknown cmd {cmd!r}"}

    def _register(self, msg: dict) -> None:
        jobid = str(msg["jobid"])
        rec = {"host": str(msg["host"]), "port": int(msg["port"]),
               "health_port": msg.get("health_port"),
               "model_id": str(msg.get("model_id") or "default")}
        with self._lock:
            self._replicas.setdefault(jobid, {}).update(rec)
            self._m_replicas.set(len(self._replicas))
        self._beat(jobid)

    def _cmd_register(self, msg: dict) -> dict:
        self._register(msg)
        log_info("fleet registry: replica %r registered at %s:%s "
                 "(model=%s)", msg["jobid"], msg["host"], msg["port"],
                 msg.get("model_id") or "default")
        return {"ok": True}

    def _cmd_deregister(self, msg: dict) -> dict:
        jobid = str(msg["jobid"])
        with self._lock:
            self._replicas.pop(jobid, None)
            self._directives.pop(jobid, None)
            self._last_beat.pop(jobid, None)
            self._m_replicas.set(len(self._replicas))
        self.liveness.forget(jobid)
        self.rollouts.on_replica_gone(jobid)
        return {"ok": True}

    def _cmd_heartbeat(self, msg: dict) -> dict:
        jobid = str(msg["jobid"])
        with self._lock:
            known = jobid in self._replicas
        if not known and "host" in msg and "port" in msg:
            # auto-registration: the first beat after a registry restart
            # (or a replica that skipped explicit registration) carries
            # its address — a heartbeat IS a registration
            self._register(msg)
            log_info("fleet registry: replica %r auto-registered via "
                     "heartbeat", jobid)
        self._beat(jobid)
        report = {k: msg[k] for k in _REPORT_KEYS if k in msg}
        with self._lock:
            if jobid in self._replicas:
                self._replicas[jobid].update(report)
            directives = self._directives.pop(jobid, [])
        state = msg.get("state")
        if isinstance(state, dict):
            # metric push riding the heartbeat: feeds cross-replica
            # straggler detection, same as the data-service fleet
            self.straggler_board.update(jobid, state)
        for ack in msg.get("applied") or []:
            self.rollouts.on_ack(jobid, ack)
        return {"ok": True, "directives": directives}


class ReplicaAgent:
    """The replica-side half of the control plane, run inside a
    :class:`~dmlc_core_tpu.serving.server.PredictionServer` process.

    Registers the replica, then heartbeats at ``DMLC_ROUTER_HEARTBEAT``
    cadence carrying the live ``/healthz`` body (health word,
    queue-depth fraction, inflight), serving p99, checkpoint step and a
    full metrics-state push; applies hot-reload directives carried in
    heartbeat replies and acks them on the next beat.  A dead registry
    never takes the replica down: failed beats log at most once per
    outage and the loop keeps probing (the next successful beat
    re-registers via the heartbeat auto-registration path).

    ``report_overrides`` lets tests and operators force report fields
    (e.g. ``{"slo_breaches": 1}`` to drill the canary auto-rollback).
    """

    def __init__(self, server: Any, registry_addr: Tuple[str, int], *,
                 jobid: Optional[str] = None,
                 model_id: Optional[str] = None,
                 interval_s: Optional[float] = None):
        self.server = server
        self.registry_addr = (str(registry_addr[0]), int(registry_addr[1]))
        self.jobid = jobid or f"replica-{server.host}:{server.port}"
        self.model_id = (model_id or getattr(server, "model_id", None)
                         or "default")
        if interval_s is None:
            interval_s = get_env("DMLC_ROUTER_HEARTBEAT", 1.0)
        self.interval_s = max(0.05, float(interval_s))
        self.report_overrides: Dict[str, Any] = {}
        self._acks: List[dict] = []
        self._lock = threading.Lock()
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry_down = False

    # -- report assembly -------------------------------------------------
    def _report(self) -> Dict[str, Any]:
        doc = self.server.health_doc() if hasattr(self.server,
                                                  "health_doc") else {}
        snap = metrics.snapshot()
        lat = snap.get("serving.latency_s") or {}
        reqs = snap.get("serving.batcher.requests") or {}
        engine = getattr(self.server, "engine", None)
        report: Dict[str, Any] = {
            "jobid": self.jobid, "host": self.server.host,
            "port": self.server.port, "model_id": self.model_id,
            "health": doc.get("status", "ok"),
            "queue_fraction": doc.get("queue_fraction", 0.0),
            "queue_depth": doc.get("queue_depth", 0),
            "inflight": doc.get("inflight", 0),
            "p99_ms": float(lat.get("p99", 0.0) or 0.0) * 1e3,
            "qps": float(reqs.get("windowed_rate",
                                  reqs.get("rate", 0.0)) or 0.0),
            "step": getattr(self, "_step", None),
            "params_version": getattr(engine, "params_version", None),
            "slo_breaches": int(
                metrics.gauge("slo.active_breaches").value),
            "state": snap,
        }
        telemetry = getattr(self.server, "telemetry", None)
        if telemetry is not None:
            report["health_port"] = telemetry.port
        report.update(self.report_overrides)
        return report

    def _apply(self, directive: dict) -> None:
        kind = directive.get("kind")
        ack = {"rollout_id": directive.get("rollout_id"), "kind": kind}
        if kind == "reload":
            try:
                step = self.server.reload_from_checkpoint(
                    str(directive["ckpt_dir"]), directive.get("step"))
                self._step = step
                ack.update(ok=True, step=step)
            except Exception as e:  # noqa: BLE001 — a bad checkpoint must
                # not kill the replica; the registry learns via the ack
                ack.update(ok=False, error=str(e))
                logger.warning("fleet agent %s: reload directive failed: "
                               "%s", self.jobid, e)
        else:
            ack.update(ok=False, error=f"unknown directive {kind!r}")
        with self._lock:
            self._acks.append(ack)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ReplicaAgent":
        try:
            fleet_rpc(self.registry_addr,
                      {"cmd": "register_replica", **self._report()},
                      timeout=5.0)
        except (OSError, DMLCError) as e:
            # heartbeat auto-registration picks this up once the
            # registry is reachable
            logger.warning("fleet agent %s: registration deferred (%s)",
                           self.jobid, e)
        self._thread = threading.Thread(target=self._run,
                                        name=f"fleet-agent-{self.jobid}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        try:
            fleet_rpc(self.registry_addr,
                      {"cmd": "deregister_replica", "jobid": self.jobid},
                      timeout=2.0)
        except (OSError, DMLCError):
            pass               # registry gone — its sweep will notice

    def _run(self) -> None:
        # jittered beats (±DMLC_HEARTBEAT_JITTER): a restarted registry
        # must not absorb every agent's re-registration in one instant
        while not self._stop_ev.wait(jittered(self.interval_s)):
            msg = {"cmd": "heartbeat", **self._report()}
            with self._lock:
                if self._acks:
                    msg["applied"], self._acks = self._acks, []
            try:
                reply = fleet_rpc(self.registry_addr, msg, timeout=5.0)
            except (OSError, DMLCError) as e:
                if not self._registry_down:
                    self._registry_down = True
                    logger.warning("fleet agent %s: heartbeat failed "
                                   "(%s) — will keep probing", self.jobid, e)
                with self._lock:
                    # re-queue unacked directives' acks for the next beat
                    self._acks = msg.get("applied", []) + self._acks
                continue
            self._registry_down = False
            for directive in reply.get("directives") or []:
                self._apply(directive)
