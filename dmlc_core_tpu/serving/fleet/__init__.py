"""Replicated serving fleet: registry control plane, routing front-end,
canary checkpoint rollout.

The serving tier's horizontal story (ROADMAP "[scale/serving]"), in the
shape of the data-service dispatcher (PR 10, tf.data-service lineage —
PAPERS.md arxiv 2210.14826): a small JSON-line control plane owns
membership and liveness while the data plane stays on the existing
pipelined serving wire protocol.

* :mod:`registry` — :class:`ReplicaRegistry` (auto-registration,
  heartbeat liveness, multi-model map) + :class:`ReplicaAgent` (runs
  inside a replica: registers, heartbeats, applies reload directives).
* :mod:`router`   — :class:`ServingRouter`, a pipelined TCP front-end
  fanning requests across replicas with least-loaded pick-2 weighting,
  degraded-drain, straggler eviction and replica-aware retry budgets.
* :mod:`rollout`  — :class:`RolloutManager`, canary checkpoint rollout:
  stage a hot-reload on a replica subset, bake against SLO/p99 deltas,
  promote fleet-wide or auto-roll-back, every transition in a bounded
  ledger served at ``/rollouts`` and attached to flight bundles.

See docs/serving.md ("Serving fleet") for topology and knobs.
"""

from .registry import ReplicaAgent, ReplicaRegistry, fleet_rpc  # noqa: F401
from .rollout import RolloutManager  # noqa: F401
from .router import ServingRouter  # noqa: F401

__all__ = ["ReplicaRegistry", "ReplicaAgent", "fleet_rpc",
           "ServingRouter", "RolloutManager"]
