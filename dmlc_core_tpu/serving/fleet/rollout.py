"""Canary checkpoint rollout over the replica fleet.

A rollout moves one model's stable checkpoint pointer in three acts:

1. **stage** — pick a canary subset (``DMLC_CANARY_FRACTION`` of the
   alive replicas, at least one, never all when the fleet has >1) and
   queue a hot-reload directive for each; everyone else keeps serving
   the stable checkpoint as the control group.
2. **bake** — for ``DMLC_CANARY_BAKE_S`` the watch loop compares the
   canaries against the control group on every heartbeat: any canary
   SLO breach (``slo.active_breaches`` pushed in its report, i.e. the
   ``DMLC_SLO_SPEC`` machinery), any failed reload ack, or canary mean
   p99 above ``DMLC_CANARY_P99_RATIO`` × stable mean p99 trips a
   **breach**.
3. **promote or roll back** — a clean bake (all canaries acked, no
   breach) moves the stable pointer and reloads the rest of the fleet;
   a breach queues reload-to-stable directives for the canaries and
   leaves the pointer alone.

Every transition lands in a bounded ledger (``DMLC_CANARY_LEDGER_CAP``
events) served at ``/rollouts`` and attached to flight bundles via the
``rollout_ledger`` contributor, so a bad-canary incident bundle carries
the full promote/rollback history.

Directives are pull-based (heartbeat replies — see :mod:`.registry`),
so a rollout advances at heartbeat cadence; bake windows shorter than a
couple of beats cannot observe the canary and will hit the stale guard.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ...utils.logging import get_logger, log_info
from ...utils.metrics import metrics
from ...utils.parameter import get_env

__all__ = ["RolloutManager"]

logger = get_logger()

#: ignore p99 ratios when both sides are under this floor — loopback
#: noise, not a regression
_P99_NOISE_FLOOR_MS = 1.0


class RolloutManager:
    """Owns canary rollouts for a :class:`~.registry.ReplicaRegistry`.

    One active rollout per ``model_id``; staging a second for the same
    model while one is in flight is an error (roll it back or let it
    bake out first).  All decisions run in a single watch thread, so
    state transitions are serialized per manager.

    Rollout state is durable (r17): every transition forwards a journal
    record through the owning registry's ``_jlog`` (a no-op without a
    journal), so a SIGKILLed registry replays its active canaries,
    pending acks, and ledger, and the bake resumes — the bake window
    restarts from the replay (conservative), and the atomic
    ``rollout_finished`` record guarantees a promote is applied exactly
    once across restarts.
    """

    _DURABLE_STATE = ("_active", "_ledger", "_seq")

    def __init__(self, registry: Any, *,
                 bake_s: Optional[float] = None,
                 p99_ratio: Optional[float] = None,
                 fraction: Optional[float] = None,
                 ledger_cap: Optional[int] = None):
        self.registry = registry
        if bake_s is None:
            bake_s = get_env("DMLC_CANARY_BAKE_S", 30.0)
        if p99_ratio is None:
            p99_ratio = get_env("DMLC_CANARY_P99_RATIO", 1.5)
        if fraction is None:
            fraction = get_env("DMLC_CANARY_FRACTION", 0.25)
        if ledger_cap is None:
            ledger_cap = get_env("DMLC_CANARY_LEDGER_CAP", 256)
        self.bake_s = float(bake_s)
        self.p99_ratio = float(p99_ratio)
        self.fraction = min(1.0, max(0.0, float(fraction)))
        self._lock = threading.Lock()
        #: model_id → active rollout record
        self._active: Dict[str, Dict[str, Any]] = {}
        self._ledger: deque = deque(maxlen=max(16, int(ledger_cap)))
        self._seq = 0
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- durability (r17) ------------------------------------------------
    def _jlog(self, op: str, **fields: Any) -> None:
        """Forward a journal record to the owning registry's journal;
        called with no locks held (the registry's compaction path takes
        its journal mutex before the rollout lock)."""
        reg_jlog = getattr(self.registry, "_jlog", None)
        if reg_jlog is not None:
            reg_jlog(op, **fields)

    def durable_snapshot(self) -> Dict[str, Any]:
        """The rollout slice of the registry's journal snapshot —
        JSON-form active rollouts (sets as sorted lists, no monotonic
        clocks) + ledger + the rollout-id sequence."""
        with self._lock:
            return {
                "active": {
                    m: {"id": r["id"], "model_id": r["model_id"],
                        "ckpt_dir": r["ckpt_dir"], "step": r["step"],
                        "canaries": list(r["canaries"]),
                        "bake_s": r["bake_s"],
                        "acked": sorted(r["acked"]),
                        "failed": sorted(r["failed"])}
                    for m, r in self._active.items()},
                "ledger": list(self._ledger),
                "seq": self._seq,
            }

    def _restore_state(self, state: Dict[str, Any]) -> None:
        """Rebuild from a replayed journal state.  ``staged_at`` is a
        monotonic clock that did not survive the crash, so the bake
        window restarts now — a restored canary bakes a full window
        before promoting, never a truncated one."""
        if not state:
            return
        now = time.monotonic()
        with self._lock:
            self._active = {
                str(m): {"id": r.get("id"), "model_id": str(m),
                         "ckpt_dir": r.get("ckpt_dir"),
                         "step": r.get("step"),
                         "canaries": list(r.get("canaries") or []),
                         "bake_s": float(r.get("bake_s") or self.bake_s),
                         "staged_at": now,
                         "acked": set(r.get("acked") or []),
                         "failed": set(r.get("failed") or [])}
                for m, r in (state.get("active") or {}).items()}
            self._ledger.clear()
            self._ledger.extend(state.get("ledger") or [])
            self._seq = max(self._seq, int(state.get("seq") or 0))
        if self._active:
            log_info("rollout manager: restored %d active rollout(s) "
                     "from journal — bake window restarted",
                     len(self._active))

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._watch_loop,
                                        name="fleet-rollout-watch",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- staging ---------------------------------------------------------
    def stage(self, model_id: str, ckpt_dir: str, *,
              step: Optional[int] = None,
              fraction: Optional[float] = None,
              bake_s: Optional[float] = None) -> Dict[str, Any]:
        """Stage ``ckpt_dir`` on a canary subset of ``model_id``'s
        replicas; returns ``{"rollout_id", "canaries"}``."""
        frac = self.fraction if fraction is None else float(fraction)
        bake = self.bake_s if bake_s is None else float(bake_s)
        records = self.registry.replica_records(model_id)
        alive = sorted(j for j, r in records.items() if r.get("alive"))
        if not alive:
            return {"error": f"no live replicas serve model {model_id!r}"}
        n = max(1, math.ceil(frac * len(alive)))
        if len(alive) > 1:
            n = min(n, len(alive) - 1)   # keep a control group
        canaries = alive[:n]
        with self._lock:
            if model_id in self._active:
                return {"error": f"rollout {self._active[model_id]['id']}"
                                 f" already in flight for {model_id!r}"}
            self._seq += 1
            rid = f"ro-{self._seq}"
            seq = self._seq
            self._active[model_id] = {
                "id": rid, "model_id": model_id, "ckpt_dir": ckpt_dir,
                "step": step, "canaries": canaries, "bake_s": bake,
                "staged_at": time.monotonic(), "acked": set(),
                "failed": set(),
            }
        self._jlog("rollout_staged", seq=seq, rollout={
            "id": rid, "model_id": model_id, "ckpt_dir": ckpt_dir,
            "step": step, "canaries": canaries, "bake_s": bake})
        for jobid in canaries:
            self.registry.push_directive(jobid, {
                "kind": "reload", "rollout_id": rid,
                "ckpt_dir": ckpt_dir, "step": step})
        metrics.counter("fleet.rollouts.staged").add(1)
        self._record("staged", rid, model_id, ckpt_dir=ckpt_dir,
                     step=step, canaries=canaries, bake_s=bake)
        log_info("rollout %s: staged %s (step=%s) on canaries %s "
                 "(bake %.1fs)", rid, ckpt_dir, step, canaries, bake)
        return {"rollout_id": rid, "canaries": canaries}

    # -- heartbeat hooks (called by the registry) ------------------------
    def on_ack(self, jobid: str, ack: dict) -> None:
        """A replica acked a reload directive on its heartbeat."""
        rid = ack.get("rollout_id")
        with self._lock:
            ro = next((r for r in self._active.values()
                       if r["id"] == rid), None)
            if ro is None:
                return          # promote/rollback ack, or stale
            if ack.get("ok"):
                ro["acked"].add(jobid)
            else:
                ro["failed"].add(jobid)
                ro["fail_reason"] = ack.get("error")
        self._jlog("rollout_ack", jobid=jobid, rollout_id=rid,
                   ok=bool(ack.get("ok")), error=ack.get("error"))

    def on_replica_gone(self, jobid: str) -> None:
        """A canary that deregisters mid-bake stops counting toward the
        all-acked promotion condition."""
        touched = False
        with self._lock:
            for ro in self._active.values():
                if jobid in ro["canaries"]:
                    ro["canaries"] = [j for j in ro["canaries"]
                                      if j != jobid]
                    ro["acked"].discard(jobid)
                    touched = True
        if touched:
            self._jlog("rollout_gone", jobid=jobid)

    # -- bake evaluation -------------------------------------------------
    def _watch_loop(self) -> None:
        while not self._stop_ev.is_set():
            with self._lock:
                bakes = [r["bake_s"] for r in self._active.values()]
            shortest = min(bakes) if bakes else 1.0
            if self._stop_ev.wait(max(0.05, min(1.0, shortest / 8.0))):
                return
            self.evaluate_once()

    def evaluate_once(self) -> None:
        """One bake-evaluation pass (the watch thread's body; tests call
        it directly for determinism)."""
        with self._lock:
            active = list(self._active.values())
        for ro in active:
            try:
                self._evaluate(ro)
            except Exception as e:  # noqa: BLE001 — one broken rollout
                # must not stall the watch loop for every model
                logger.warning("rollout %s: evaluation error: %s",
                               ro["id"], e)

    def _evaluate(self, ro: Dict[str, Any]) -> None:
        model_id = ro["model_id"]
        records = self.registry.replica_records(model_id)
        canaries = {j: r for j, r in records.items()
                    if j in ro["canaries"]}
        stable = {j: r for j, r in records.items()
                  if j not in ro["canaries"] and r.get("alive")}
        breach = self._breach_reason(ro, canaries, stable)
        if breach:
            self._finish(ro, promoted=False, reason=breach)
            return
        elapsed = time.monotonic() - ro["staged_at"]
        all_acked = (bool(ro["canaries"])
                     and ro["acked"] >= set(ro["canaries"]))
        if all_acked and elapsed >= ro["bake_s"]:
            self._finish(ro, promoted=True,
                         reason=f"baked {elapsed:.1f}s clean")
        elif not all_acked and elapsed > 4.0 * ro["bake_s"] + 10.0:
            # stale guard: canaries never picked the directive up
            # (heartbeats stopped, reload hung) — treat as a breach
            self._finish(ro, promoted=False,
                         reason="canaries never acked reload")

    def _breach_reason(self, ro: Dict[str, Any],
                       canaries: Dict[str, dict],
                       stable: Dict[str, dict]) -> Optional[str]:
        if ro["failed"]:
            return (f"reload failed on {sorted(ro['failed'])}: "
                    f"{ro.get('fail_reason')}")
        breached = [j for j, r in canaries.items()
                    if int(r.get("slo_breaches") or 0) > 0]
        if breached:
            return f"SLO breach on canaries {breached}"
        dead = [j for j in ro["canaries"]
                if j in canaries and not canaries[j].get("alive")]
        if dead:
            return f"canaries died mid-bake: {dead}"
        # p99 delta vs the control group — only meaningful once the
        # canaries acked (pre-reload latency describes the old ckpt)
        if stable and ro["acked"]:
            c_p99 = [float(r.get("p99_ms") or 0.0)
                     for j, r in canaries.items() if j in ro["acked"]]
            s_p99 = [float(r.get("p99_ms") or 0.0)
                     for r in stable.values()]
            c = sum(c_p99) / len(c_p99) if c_p99 else 0.0
            s = sum(s_p99) / len(s_p99) if s_p99 else 0.0
            if (c > _P99_NOISE_FLOOR_MS
                    and c > self.p99_ratio * max(s, _P99_NOISE_FLOOR_MS)):
                return (f"canary p99 {c:.2f}ms > {self.p99_ratio:g}x "
                        f"stable {s:.2f}ms")
        return None

    def _finish(self, ro: Dict[str, Any], *, promoted: bool,
                reason: str) -> None:
        model_id = ro["model_id"]
        with self._lock:
            if self._active.get(model_id) is not ro:
                return          # already finished by another path
            del self._active[model_id]
        # one atomic journal record closes the rollout AND (on promote)
        # moves the stable pointer: replay can never re-promote a closed
        # rollout, which is what makes promotion exactly-once across
        # registry crashes
        self._jlog("rollout_finished", model_id=model_id,
                   rollout_id=ro["id"], promoted=promoted,
                   ckpt_dir=ro["ckpt_dir"], step=ro["step"],
                   reason=reason)
        if promoted:
            self.registry.set_stable_pointer(model_id, ro["ckpt_dir"],
                                             ro["step"])
            # fleet-wide reload: every non-canary replica follows
            records = self.registry.replica_records(model_id)
            rest = [j for j, r in records.items()
                    if j not in ro["canaries"] and r.get("alive")]
            for jobid in rest:
                self.registry.push_directive(jobid, {
                    "kind": "reload", "rollout_id": f"{ro['id']}-promote",
                    "ckpt_dir": ro["ckpt_dir"], "step": ro["step"]})
            metrics.counter("fleet.rollouts.promoted").add(1)
            self._record("promoted", ro["id"], model_id,
                         ckpt_dir=ro["ckpt_dir"], step=ro["step"],
                         reason=reason, reloaded=rest)
            log_info("rollout %s: PROMOTED %s for model %s (%s)",
                     ro["id"], ro["ckpt_dir"], model_id, reason)
        else:
            stable = self.registry.stable_pointer(model_id)
            rollback_dir = stable.get("ckpt_dir")
            for jobid in ro["canaries"]:
                if rollback_dir is not None:
                    self.registry.push_directive(jobid, {
                        "kind": "reload",
                        "rollout_id": f"{ro['id']}-rollback",
                        "ckpt_dir": rollback_dir,
                        "step": stable.get("step")})
            metrics.counter("fleet.rollouts.rolled_back").add(1)
            self._record("rolled_back", ro["id"], model_id,
                         ckpt_dir=ro["ckpt_dir"], reason=reason,
                         rollback_to=rollback_dir)
            logger.warning("rollout %s: ROLLED BACK for model %s — %s",
                           ro["id"], model_id, reason)

    # -- ledger ----------------------------------------------------------
    def _record(self, event: str, rid: str, model_id: str,
                **attrs: Any) -> None:
        ev = {"ts": time.time(), "event": event, "rollout_id": rid,
              "model_id": model_id, **attrs}
        self._jlog("rollout_event", event=ev)
        with self._lock:
            self._ledger.append(ev)

    def snapshot(self) -> Dict[str, Any]:
        """The ``/rollouts`` body and the ``rollout_ledger`` flight
        contributor: active rollouts + the bounded event ledger."""
        with self._lock:
            active = {
                m: {"id": r["id"], "ckpt_dir": r["ckpt_dir"],
                    "step": r["step"], "canaries": list(r["canaries"]),
                    "acked": sorted(r["acked"]),
                    "failed": sorted(r["failed"]),
                    "bake_s": r["bake_s"],
                    "elapsed_s": round(time.monotonic() - r["staged_at"],
                                       3)}
                for m, r in self._active.items()}
            events = list(self._ledger)
        return {"schema": "dmlc.serving.rollouts/1", "ts": time.time(),
                "active": active, "events": events}
