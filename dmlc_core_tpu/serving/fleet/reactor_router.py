"""Reactor-backed connection fabric for :class:`ServingRouter`.

The threaded router burns one thread per client connection plus one per
backend link — ~8 MB of stack each, so 10k mostly-idle predict
connections cost ~80 GB of address space and a scheduler meltdown long
before any socket limit.  This fabric re-plumbs *transport only*:

* **Upstream** predict connections become reactor-managed state
  machines (:class:`~...transport.reactor.FrameAssembler` over the
  unchanged ``REQ_HEADER`` wire layout) spread across
  ``DMLC_REACTOR_LOOPS`` loops (``SO_REUSEPORT``-sharded listeners).
* **Downstream** replica links are pooled — one connection per replica,
  multiplexed by backend req_id, all owned by the primary loop — so a
  hedge or failover is a queue move, not a new thread.
* **Policy stays in router.py**: replica selection (``_pick``), the
  retry budget and hedge/failover bookkeeping (``_hedge_target``),
  response finishing, spans and wide events are the same code the
  threaded path runs; :meth:`ServingRouter._dispatch_any` routes only
  the transport step here.  Byte layout on both legs is identical, so
  ``PredictClient`` and the replicas can't tell the fabrics apart.

Threading contract: all fabric state (``_RBackend`` maps, queues) is
touched only on the **primary** loop; frontend loops and the health /
sync threads funnel dispatch through ``call_soon``.  Replies travel
back via :meth:`Connection.write`, which is safe from any thread.
Blocking backend connects run on the reactor's bounded executor (one
connect per replica link, not per request).
"""

from __future__ import annotations

import socket
from typing import Dict, List, Optional

from ...telemetry import trace as teltrace
from ...transport.listener import Listener
from ...transport.reactor import (Connection, FrameAssembler, Reactor,
                                  ReactorGroup, reactor_loops)
from ...utils.logging import get_logger, log_info
from ...utils.metrics import metrics
from ...utils.parameter import get_env
from ...utils.retry import CircuitOpen
from ..server import (HELLO_REQ_ID, REQ_HEADER, RSP_HEADER, STATUS_OK,
                      _MAX_NNZ, _MAX_ROWS, pack_hello)

__all__ = ["RouterFabric"]

logger = get_logger()

STATUS_BAD_REQUEST = 5          # mirror of server.STATUS_BAD_REQUEST


class _RClient:
    """Reactor-side client connection — duck-typed to ``_ClientConn``
    (``respond``/``model_id``/``alive``), so ``_Pending`` and the
    response/wide-event path in router.py need no mode branches."""

    __slots__ = ("cid", "conn", "model_id", "alive")

    def __init__(self, cid: int, conn: Connection):
        self.cid = cid
        self.conn = conn
        self.model_id = "default"
        self.alive = True

    def respond(self, req_id: int, status: int, payload: bytes) -> None:
        n = len(payload) // 4 if status == STATUS_OK else len(payload)
        self.conn.write(RSP_HEADER.pack(req_id, status, n) + payload)


class _RBackend:
    """One pooled replica link: ``idle`` (no socket) → ``connecting``
    (executor dial in flight, frames queue) → ``up`` (hello sent,
    queue flushed).  Primary-loop state only."""

    __slots__ = ("rep", "state", "conn", "queue")

    def __init__(self, rep):
        self.rep = rep
        self.state = "idle"
        self.conn: Optional[Connection] = None
        self.queue: List[bytes] = []    # frames awaiting connect


class RouterFabric:
    """Owns the reactor group and both protocol legs for one router."""

    def __init__(self, router, listeners: List[Listener]):
        self._r = router
        self._listeners = listeners
        n = reactor_loops()
        self.group = ReactorGroup(
            n, "router-reactor",
            executor_workers=int(get_env("DMLC_REACTOR_EXECUTOR", 2)),
            idle_s=float(get_env("DMLC_REACTOR_IDLE_S", 0.0)))
        self.primary: Reactor = self.group.primary
        self._backends: Dict[str, _RBackend] = {}   # primary loop only

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "RouterFabric":
        if len(self._listeners) != len(self.group.loops):
            # loops were env-resolved after bind (single listener): every
            # loop still works, but only loop 0 accepts
            loops = self.group.loops[:len(self._listeners)] or \
                [self.primary]
        else:
            loops = self.group.loops
        for r, lst in zip(loops, self._listeners):
            r.add_listener(
                lst.sock,
                lambda sock, addr, _r=r: self._on_client(_r, sock))
        self.group.start()
        log_info("router fabric: %d loop(s), %d listener(s)",
                 len(self.group), len(self._listeners))
        return self

    def stop(self) -> None:
        for lst in self._listeners:
            lst.close()
        self.group.stop()

    # -- frontend (any loop) ---------------------------------------------
    def _on_client(self, reactor: Reactor, sock: socket.socket) -> None:
        with self._r._conn_lock:
            cid = self._r._next_conn
            self._r._next_conn += 1
        asm = FrameAssembler(REQ_HEADER.size, self._front_header,
                             self._front_frame)
        conn = reactor.add_connection(
            sock, lambda c, view: asm.feed(c, view),
            on_close=self._front_closed)
        conn.data = _RClient(cid, conn)

    def _front_closed(self, conn: Connection,
                      exc: Optional[BaseException]) -> None:
        rc: _RClient = conn.data
        if rc is not None:
            rc.alive = False

    def _front_header(self, conn: Connection,
                      header: bytes) -> Optional[int]:
        req_id, trace_id, parent_span, rows, nnz = REQ_HEADER.unpack(
            header)
        if req_id == HELLO_REQ_ID:
            return nnz
        if rows == 0 or rows > _MAX_ROWS or nnz > _MAX_NNZ:
            rc: _RClient = conn.data
            rc.respond(req_id, STATUS_BAD_REQUEST,
                       f"bad header rows={rows} nnz={nnz}".encode())
            conn.close_after_flush()
            return None
        return 4 * (rows + 1) + 8 * nnz

    def _front_frame(self, conn: Connection, header: bytes,
                     payload: bytes) -> None:
        rc: _RClient = conn.data
        req_id, trace_id, parent_span, rows, nnz = REQ_HEADER.unpack(
            header)
        if req_id == HELLO_REQ_ID:
            rc.model_id = payload.decode("utf-8", "replace") or "default"
            return
        r = self._r
        r._m_requests.add(1)
        span = None
        if trace_id:
            span = teltrace.start_span(
                "serving.router.request",
                parent=teltrace.TraceContext(trace_id, parent_span),
                req_id=req_id, rows=rows, model=rc.model_id)
        with r._plock:
            bid = r._next_bid
            r._next_bid += 1
        pend = r._make_pending(bid, rc, req_id, trace_id, parent_span,
                               rows, nnz, payload, span)
        if span is not None:
            pend.trace_id = span.context.trace_id
            pend.parent_span = span.context.span_id
        with r._plock:
            r._pending[bid] = pend
            r._m_inflight.set(len(r._pending))
        target = r._pick(rc.model_id, pend.tried)
        if target is None:
            with r._plock:
                r._pending.pop(bid, None)
            r._respond_shed(pend, f"no replica available for model "
                                  f"{rc.model_id!r}")
            return
        self.dispatch(pend, target)

    # -- dispatch (funnelled to the primary loop) ------------------------
    def dispatch(self, pend, rep) -> bool:
        """Transport step for one (pend, replica) decision.  Always
        True: queued-while-connecting counts as dispatched, and the
        loop-side walk owns the shed on ultimate failure."""
        if self.primary.in_loop():
            self._dispatch_on_loop(pend, rep)
        else:
            self.primary.call_soon(self._dispatch_on_loop, pend, rep)
        return True

    def _dispatch_on_loop(self, pend, rep) -> None:
        """Mirror of the threaded ``_dispatch`` candidate walk, with the
        blocking send replaced by a queue move on the pooled link."""
        r = self._r
        while True:
            pend.attempts += 1
            pend.tried.add(rep.key)
            pend.replica_key = rep.key
            try:
                rep.breaker.allow()
            except CircuitOpen:
                nxt = None
                if pend.attempts < r._retry.max_attempts:
                    nxt = r._pick(pend.client.model_id, pend.tried)
                if nxt is None:
                    with r._plock:
                        r._pending.pop(pend.bid, None)
                    r._respond_shed(pend, f"no replica available for "
                                          f"model "
                                          f"{pend.client.model_id!r}")
                    return
                r._m_retries.add(1)
                pend.failovers += 1
                if pend.span is not None:
                    pend.span.event("failover", frm=rep.key, to=nxt.key,
                                    reason="CircuitOpen")
                rep = nxt
                continue
            with rep.lock:
                rep.outstanding.add(pend.bid)
                rep.inflight += 1
            frame = REQ_HEADER.pack(pend.bid, pend.trace_id,
                                    pend.parent_span, pend.rows,
                                    pend.nnz) + pend.tail
            be = self._backends.get(rep.key)
            if be is None or be.rep is not rep:
                be = _RBackend(rep)
                self._backends[rep.key] = be
            if be.state == "up":
                be.conn.write(frame)
            else:
                be.queue.append(frame)
                if be.state == "idle":
                    self._start_connect(be)
            return

    # -- backend link (primary loop) -------------------------------------
    def _start_connect(self, be: _RBackend) -> None:
        be.state = "connecting"
        rep = be.rep

        def dial() -> socket.socket:
            sock = socket.create_connection((rep.host, rep.port),
                                            timeout=5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock

        def on_done(sock, exc) -> None:
            if exc is not None:
                self._connect_failed(be, exc)
            else:
                self._connected(be, sock)

        self.primary.executor.submit(dial, on_done)

    def _connected(self, be: _RBackend, sock: socket.socket) -> None:
        rep = be.rep
        if self._backends.get(rep.key) is not be or self._r._stopping:
            try:
                sock.close()
            except OSError:
                pass
            return
        asm = FrameAssembler(
            RSP_HEADER.size,
            lambda conn, head: self._back_header(be, conn, head),
            lambda conn, head, payload: self._back_frame(be, head,
                                                         payload))
        conn = self.primary.add_connection(
            sock, lambda c, view: asm.feed(c, view),
            on_close=lambda c, exc: self._backend_lost(be, exc),
            idle_s=0.0)             # pooled links never idle out
        be.conn = conn
        be.state = "up"
        rep.fabric_connected = True
        # model hello first, then everything queued while connecting —
        # same first-frame discipline as the threaded _ensure_backend
        conn.write(pack_hello(rep.model_id))
        queued, be.queue = be.queue, []
        for frame in queued:
            conn.write(frame)

    def _connect_failed(self, be: _RBackend,
                        exc: BaseException) -> None:
        rep = be.rep
        if self._backends.get(rep.key) is be:
            self._backends.pop(rep.key, None)
        be.state = "idle"
        rep.breaker.record_failure()
        be.queue.clear()
        self._refan(rep, exc)

    def _back_header(self, be: _RBackend, conn: Connection,
                     head: bytes) -> Optional[int]:
        bid, status, n = RSP_HEADER.unpack(head)
        return 4 * n if status == STATUS_OK else n

    def _back_frame(self, be: _RBackend, head: bytes,
                    payload: bytes) -> None:
        bid, status, n = RSP_HEADER.unpack(head)
        if bid == HELLO_REQ_ID:
            logger.warning("router fabric: replica %s refused model "
                           "hello: %s", be.rep.key,
                           payload.decode("utf-8", "replace"))
            if be.conn is not None:
                be.conn.kill()
            return
        # policy unchanged: hedge-on-shed, breaker bookkeeping, span
        # end, wide event — router.py owns all of it
        self._r._on_backend_response(be.rep, bid, status, payload)

    def _backend_lost(self, be: _RBackend,
                      exc: Optional[BaseException]) -> None:
        rep = be.rep
        if self._backends.get(rep.key) is be:
            self._backends.pop(rep.key, None)
        be.state = "idle"
        be.conn = None
        be.queue.clear()
        rep.fabric_connected = False
        if self._r._stopping:
            with rep.lock:
                rep.outstanding.clear()
                rep.inflight = 0
            return
        rep.breaker.record_failure()
        self._refan(rep, exc or ConnectionError("replica link closed"))

    def _refan(self, rep, exc: BaseException) -> None:
        """Mirror of the threaded ``_on_backend_lost`` orphan path."""
        r = self._r
        with rep.lock:
            orphans = list(rep.outstanding)
            rep.outstanding.clear()
            rep.inflight = 0
        if not orphans:
            return
        logger.warning("router: lost replica %s (%s) — refanning %d "
                       "in-flight request(s)", rep.key, exc,
                       len(orphans))
        for bid in orphans:
            with r._plock:
                pend = r._pending.get(bid)
            if pend is None:
                continue
            metrics.counter("serving.router.failovers").add(1)
            if not r._try_failover(pend, rep, reason="conn_lost",
                                   already_released=True):
                with r._plock:
                    r._pending.pop(bid, None)
                r._respond_shed(pend, f"replica {rep.key} lost: {exc}")

    def drop_backend(self, rep) -> None:
        """Registry said the replica left: close its pooled link (loop-
        side; safe from the sync thread)."""
        def do() -> None:
            be = self._backends.get(rep.key)
            if be is not None and be.rep is rep and be.conn is not None:
                be.conn.kill()
        self.primary.call_soon(do)
