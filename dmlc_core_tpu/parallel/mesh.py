"""Device mesh construction and axis conventions.

The reference's parallelism is rank-based (tracker assigns ranks, data is
sharded by ``ResetPartition(rank, nsplit)``, SURVEY §2.5).  The TPU-native
equivalent is a named :class:`jax.sharding.Mesh`; ranks become mesh
coordinates and XLA emits the collectives.

Axis conventions used across the framework:

* ``dp`` — data parallel (batch leading axis; gradient all-reduce over ICI)
* ``mp`` — model parallel (FM factor dim / embedding dim sharding)
* ``sp`` — sequence/context parallel (ring attention layer, ops.ring)

``make_mesh("dp=4,mp=2")`` builds a mesh from a spec string; unmentioned
capacity folds into the first axis.  ``process_mesh_info()`` exposes the
rank/world view (process_index ≙ the reference's ``DMLC_TASK_ID``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils import DMLCError, check

__all__ = ["make_mesh", "parse_mesh_spec", "process_mesh_info",
           "data_parallel_mesh", "row_partition", "remap_rows",
           "remap_deltas", "row_owners"]


def parse_mesh_spec(spec: str) -> Dict[str, int]:
    """Parse 'dp=4,mp=2' → {'dp': 4, 'mp': 2} (-1 allowed once: infer)."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise DMLCError(f"bad mesh spec component {part!r}")
        k, v = part.split("=", 1)
        out[k.strip()] = int(v)
    check(list(out.values()).count(-1) <= 1, "at most one -1 axis")
    return out


def make_mesh(spec: str = "dp=-1",
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh from a spec string over the given (default: all)
    devices."""
    devices = list(devices if devices is not None else jax.devices())
    axes = parse_mesh_spec(spec)
    known = 1
    for v in axes.values():
        if v > 0:
            known *= v
    n = len(devices)
    if -1 in axes.values():
        check(n % known == 0,
              f"{n} devices not divisible by fixed axes product {known}")
        axes = {k: (n // known if v == -1 else v) for k, v in axes.items()}
    total = int(np.prod(list(axes.values())))
    check(total <= n, f"mesh wants {total} devices, have {n}")
    if total < n and n % total == 0:
        # fold unused capacity into the first axis so no chip idles silently
        first = next(iter(axes))
        axes[first] *= n // total
        total = n
    mesh_devices = np.array(devices[:total]).reshape(*axes.values())
    return Mesh(mesh_devices, tuple(axes.keys()))


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    return make_mesh("dp=-1", devices)


def row_partition(n_rows: int, parts: int) -> List[Tuple[int, int]]:
    """Contiguous near-equal ``[start, stop)`` row ranges — the
    reference's ``ResetPartition(rank, nsplit)`` contract, reused by the
    elastic resharder as the canonical target layout for row-sharded
    leaves.  The first ``n_rows % parts`` ranges carry one extra row, so
    the layout is a pure function of ``(n_rows, parts)`` and every cohort
    member computes identical shard boundaries without communicating."""
    check(parts > 0, f"row_partition needs parts > 0, got {parts}")
    check(n_rows >= 0, f"row_partition needs n_rows >= 0, got {n_rows}")
    base, extra = divmod(n_rows, parts)
    out: List[Tuple[int, int]] = []
    start = 0
    for r in range(parts):
        stop = start + base + (1 if r < extra else 0)
        out.append((start, stop))
        start = stop
    return out


def row_owners(n_rows: int, parts: int, rows) -> "np.ndarray":
    """Vectorized inverse of :func:`row_partition`: for each global row id
    in ``rows`` (array-like of ints in ``[0, n_rows)``), the rank whose
    ``[start, stop)`` range owns it.  Because the first ``n_rows % parts``
    ranges carry one extra row, ownership is a closed form — no layout
    table or searchsorted needed — and stays a pure function of
    ``(n_rows, parts)`` like the partition itself."""
    check(parts > 0, f"row_owners needs parts > 0, got {parts}")
    rows = np.asarray(rows, dtype=np.int64)
    if rows.size and (rows.min() < 0 or rows.max() >= n_rows):
        raise DMLCError(f"row_owners: row ids outside [0, {n_rows})")
    base, extra = divmod(n_rows, parts)
    if base == 0:
        # parts > n_rows: row r lives alone in range r
        return rows.copy()
    fat = extra * (base + 1)          # rows covered by the +1 ranges
    return np.where(rows < fat, rows // (base + 1),
                    extra + (rows - fat) // base)


def remap_rows(n_rows: int, old_parts: int, new_parts: int
               ) -> List[List[Tuple[int, int, int]]]:
    """Shrink/grow remap plan: for each NEW rank, which ``(old_rank,
    start, stop)`` global row ranges feed its new shard.  Both layouts
    are :func:`row_partition`, so when the cohort resizes the resharder
    can tell every survivor exactly which peers hold the rows its new
    shard needs — e.g. 3→2: new rank 0 keeps its old rows and pulls the
    head of old rank 1's; nothing touches a checkpoint."""
    old = row_partition(n_rows, old_parts)
    plan: List[List[Tuple[int, int, int]]] = []
    for (ns, ne) in row_partition(n_rows, new_parts):
        feeds: List[Tuple[int, int, int]] = []
        for old_rank, (os_, oe) in enumerate(old):
            lo, hi = max(ns, os_), min(ne, oe)
            if lo < hi:
                feeds.append((old_rank, lo, hi))
        plan.append(feeds)
    return plan


def remap_deltas(n_rows: int, old_parts: int, new_parts: int
                 ) -> List[List[Tuple[int, int, int]]]:
    """Like :func:`remap_rows`, minus what each new rank already holds:
    for each NEW rank, only the ``(old_rank, start, stop)`` ranges it must
    FETCH — rows inside its own old range (when ``new_rank < old_parts``)
    are dropped.  This is the input the reshard round planner wants: the
    wire transfers, not the full feed map, so a resize that mostly keeps
    rows in place plans mostly-empty rounds instead of re-shipping the
    whole table."""
    old = row_partition(n_rows, old_parts)
    plan: List[List[Tuple[int, int, int]]] = []
    for new_rank, feeds in enumerate(remap_rows(n_rows, old_parts,
                                                new_parts)):
        own_s, own_e = (old[new_rank] if new_rank < old_parts
                        else (0, 0))
        deltas: List[Tuple[int, int, int]] = []
        for old_rank, lo, hi in feeds:
            if old_rank == new_rank:
                continue                      # already resident
            # clip away any overlap with rows this rank already holds
            if own_s < own_e and lo < own_e and hi > own_s:
                if lo < own_s:
                    deltas.append((old_rank, lo, own_s))
                if hi > own_e:
                    deltas.append((old_rank, own_e, hi))
            else:
                deltas.append((old_rank, lo, hi))
        plan.append(deltas)
    return plan


def process_mesh_info() -> Dict[str, int]:
    """Rank/world view of the current process (multi-host: one JAX process
    per host, reference ``DMLC_TASK_ID``/``DMLC_NUM_WORKER`` contract)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
