"""Rabit-style worker client: tracker rendezvous + host-side tree collectives.

The reference delegates allreduce *execution* to downstream rabit over
tracker-brokered TCP links (SURVEY §2.5).  For the TPU framework the
data-plane collectives ride ICI via XLA (``parallel.collectives``); this
module supplies the equivalent **host/control-plane** collectives between
processes — exactly rabit's API surface::

    with RabitContext.from_env() as rc:       # DMLC_TRACKER_URI/PORT env
        total = rc.allreduce(np.array([local_sum]))   # tree allreduce
        cfg = rc.broadcast(cfg_bytes, root=0)          # tree broadcast
        rc.tracker_print(f"rank {rc.rank} done")

Topology comes from the tracker (binary tree + recovery ring); reductions run
leaf→root then broadcast root→leaf over persistent worker⇄worker sockets.
A worker that restarts re-registers with ``cmd=recover`` and resumes with the
same rank (reference `tracker.py:279-291`).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils import DMLCError, check, get_env, log_info
from .tracker import recv_json, send_json

__all__ = ["RabitContext"]

_OPS: Dict[str, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {
    "sum": np.add,
    "max": np.maximum,
    "min": np.minimum,
    "prod": np.multiply,
}


def _send_blob(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_blob(sock: socket.socket) -> bytes:
    head = _recv_exact(sock, 8)
    (n,) = struct.unpack("<Q", head)
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise DMLCError("rabit: peer closed connection")
        out += chunk
    return bytes(out)


class RabitContext:
    """Worker-side rendezvous + collectives."""

    def __init__(self, tracker_uri: str, tracker_port: int,
                 jobid: Optional[str] = None, recover: bool = False,
                 connect_timeout: float = 60.0, connect_links: bool = True):
        self.tracker_addr = (tracker_uri, tracker_port)
        self.jobid = jobid or f"job-{os.getpid()}-{socket.gethostname()}"
        self.connect_timeout = connect_timeout
        # listener for peer links
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(16)
        self._listen_port = self._listener.getsockname()[1]
        self._peer_socks: Dict[int, socket.socket] = {}
        self._peer_lock = threading.Lock()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accepting = True
        self._accept_thread.start()
        self._register(recover)
        if connect_links:
            self._connect_links()

    @classmethod
    def from_env(cls, **kw) -> "RabitContext":
        """Bootstrap from the DMLC_* env contract (reference `local.py:21-27`)."""
        uri = get_env("DMLC_TRACKER_URI", "127.0.0.1")
        port = get_env("DMLC_TRACKER_PORT", 9091)
        jobid = os.environ.get("DMLC_TASK_ID")
        attempt = get_env("DMLC_NUM_ATTEMPT", 0)
        return cls(uri, port, jobid=jobid, recover=attempt > 0, **kw)

    # -- rendezvous --
    def _register(self, recover: bool) -> None:
        sock = socket.create_connection(self.tracker_addr,
                                        timeout=self.connect_timeout)
        send_json(sock, {"cmd": "recover" if recover else "start",
                         "jobid": self.jobid, "port": self._listen_port})
        f = sock.makefile("r")
        sock.settimeout(self.connect_timeout)
        reply = recv_json(f)
        sock.close()
        if reply is not None and "error" in reply:
            raise DMLCError(f"rabit: tracker rejected registration: "
                            f"{reply['error']}")
        if reply is None or "rank" not in reply:
            raise DMLCError(f"rabit: bad tracker reply {reply!r}")
        self.rank: int = reply["rank"]
        self.world_size: int = reply["world"]
        self.parent: int = reply["parent"]
        self.children: List[int] = reply["children"]
        self.ring_prev: int = reply["ring_prev"]
        self.ring_next: int = reply["ring_next"]
        self._addresses = {int(k): tuple(v)
                           for k, v in reply["addresses"].items()}

    # -- link management --
    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                head = _recv_exact(conn, 8)
                (peer_rank,) = struct.unpack("<q", head)
                with self._peer_lock:
                    self._peer_socks[peer_rank] = conn
            except (DMLCError, OSError, struct.error):
                # a bad handshake (reset, scanner, garbage) must never kill
                # the accept thread — later peers still need to register
                try:
                    conn.close()
                except OSError:
                    pass

    def _connect_links(self) -> None:
        """Dial peers with rank < ours; accept from ranks > ours (a
        deterministic direction avoids double links)."""
        deadline = time.monotonic() + self.connect_timeout
        needed = set(self._addresses)
        for peer in sorted(needed):
            if peer < self.rank:
                sock = self._dial(peer, deadline)
                with self._peer_lock:
                    self._peer_socks[peer] = sock
        # wait for inbound from higher ranks
        higher = {p for p in needed if p > self.rank}
        while True:
            with self._peer_lock:
                missing = higher - set(self._peer_socks)
            if not missing:
                break
            if time.monotonic() > deadline:
                raise DMLCError(f"rabit rank {self.rank}: peers {missing} "
                                f"never connected")
            time.sleep(0.01)

    def _dial(self, peer: int, deadline: float) -> socket.socket:
        host, port = self._addresses[peer]
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection((host, port), timeout=5.0)
                sock.sendall(struct.pack("<q", self.rank))
                return sock
            except OSError as e:
                last_err = e
                time.sleep(0.05)
        raise DMLCError(f"rabit rank {self.rank}: cannot reach peer {peer} "
                        f"at {host}:{port}: {last_err}")

    def _sock_to(self, peer: int) -> socket.socket:
        with self._peer_lock:
            sock = self._peer_socks.get(peer)
        if sock is None:
            raise DMLCError(f"rabit rank {self.rank}: no link to {peer}")
        return sock

    # -- collectives (binary tree: reduce up, broadcast down) --
    def allreduce(self, x: np.ndarray, op: str = "sum") -> np.ndarray:
        fn = _OPS.get(op)
        if fn is None:
            raise DMLCError(f"unknown op {op!r}; have {list(_OPS)}")
        acc = np.array(x, copy=True)
        for child in self.children:
            contrib = np.frombuffer(_recv_blob(self._sock_to(child)),
                                    dtype=acc.dtype).reshape(acc.shape)
            acc = fn(acc, contrib)
        if self.parent >= 0:
            _send_blob(self._sock_to(self.parent), acc.tobytes())
            acc = np.frombuffer(_recv_blob(self._sock_to(self.parent)),
                                dtype=acc.dtype).reshape(acc.shape)
        for child in self.children:
            _send_blob(self._sock_to(child), acc.tobytes())
        if not acc.flags.writeable:
            # frombuffer views are read-only; callers mutate results in place
            # (the reference rabit Allreduce is in-place by contract)
            acc = acc.copy()
        return acc

    def broadcast(self, obj: Any, root: int = 0) -> Any:
        """Tree broadcast of an arbitrary picklable object from ``root``.

        Same two-phase traffic pattern as allreduce (climb then descend) with
        a 'first non-empty wins' combiner, so arbitrary roots need no special
        routing and every queued blob is always consumed."""
        if self.world_size == 1:
            return obj
        payload = pickle.dumps(obj) if self.rank == root else b""
        for child in self.children:
            contrib = _recv_blob(self._sock_to(child))
            if contrib and not payload:
                payload = contrib
        if self.parent >= 0:
            _send_blob(self._sock_to(self.parent), payload)
            payload = _recv_blob(self._sock_to(self.parent))
        for child in self.children:
            _send_blob(self._sock_to(child), payload)
        if not payload:
            raise DMLCError(f"broadcast: no payload reached rank {self.rank}")
        return pickle.loads(payload)

    def allgather(self, x: np.ndarray) -> np.ndarray:
        """Gather per-rank arrays to all (via allreduce of a one-hot stack)."""
        x = np.asarray(x)
        stack = np.zeros((self.world_size,) + x.shape, x.dtype)
        stack[self.rank] = x
        return self.allreduce(stack, "sum")

    # -- misc rabit API --
    def tracker_print(self, msg: str) -> None:
        self._tracker_cmd({"cmd": "print", "msg": msg})

    def shutdown(self) -> None:
        self._tracker_cmd({"cmd": "shutdown", "jobid": self.jobid})
        self._accepting = False
        try:
            self._listener.close()
        except OSError:
            pass
        with self._peer_lock:
            for sock in self._peer_socks.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._peer_socks.clear()

    def _tracker_cmd(self, obj: dict) -> None:
        sock = socket.create_connection(self.tracker_addr, timeout=10.0)
        send_json(sock, obj)
        sock.close()

    def __enter__(self) -> "RabitContext":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


