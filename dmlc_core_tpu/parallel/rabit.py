"""Rabit-style worker client: tracker rendezvous + host-side tree collectives.

The reference delegates allreduce *execution* to downstream rabit over
tracker-brokered TCP links (SURVEY §2.5).  For the TPU framework the
data-plane collectives ride ICI via XLA (``parallel.collectives``); this
module supplies the equivalent **host/control-plane** collectives between
processes — exactly rabit's API surface::

    with RabitContext.from_env() as rc:       # DMLC_TRACKER_URI/PORT env
        total = rc.allreduce(np.array([local_sum]))   # tree allreduce
        cfg = rc.broadcast(cfg_bytes, root=0)          # tree broadcast
        rc.tracker_print(f"rank {rc.rank} done")

Topology comes from the tracker (binary tree + recovery ring); reductions run
leaf→root then broadcast root→leaf over persistent worker⇄worker sockets.

Elastic recovery (reference `tracker.py:80-135,279-291`): a restarted worker
re-registers with ``cmd=recover`` and resumes with the same rank; the tracker
bumps a **link generation** and pushes a ``reset_links`` control message to
every survivor's peer listener.  On reset each worker drops all peer sockets
(fresh sockets ⇒ no stale half-blobs), and the collective in flight aborts
with a socket error and retries after links are rebuilt at the new
generation.  Each blob is framed with the collective's sequence number so a
cohort that diverged mid-collective (some workers already completed the op —
the case the reference hands to downstream rabit's checkpoint ring) fails
loudly instead of silently mixing results.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import DMLCError, check, get_env, log_info, log_warning
from ..utils.logging import set_log_context
from ..transport.frames import pack_obj, send_all, unpack_obj
from .tracker import jittered, recv_json, send_json

__all__ = ["RabitContext"]

_OPS: Dict[str, Callable[[np.ndarray, np.ndarray], np.ndarray]] = {
    "sum": np.add,
    "max": np.maximum,
    "min": np.minimum,
    "prod": np.multiply,
}

_CTRL_RANK = -2  # listener handshake sentinel: tracker control message


def _send_blob(sock: socket.socket, payload: bytes, seq: int) -> None:
    send_all(sock, struct.pack("<qQ", seq, len(payload)) + payload)


def _recv_blob(sock: socket.socket, seq: int) -> bytes:
    head = _recv_exact(sock, 16)
    got_seq, n = struct.unpack("<qQ", head)
    payload = _recv_exact(sock, n)
    if got_seq != seq:
        raise DMLCError(
            f"rabit: collective out of sync (expected op #{seq}, peer sent "
            f"#{got_seq}) — the cohort diverged across a mid-collective "
            f"restart; resume from a checkpoint instead")
    return payload


def _enable_keepalive(sock: socket.socket) -> None:
    """Bound dead-HOST detection on blocking peer links: a silent network
    partition (no RST/FIN — NIC death, cable pull) would otherwise hang a
    blocking recv forever, because the tracker-reset interrupter only
    fires when a launcher respawns a worker that exited.  Kernel
    keepalives (~60s idle + 6×10s probes where tunable) surface such a
    partition as an OSError, which re-enters the normal recovery path."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    for opt, val in (("TCP_KEEPIDLE", 60), ("TCP_KEEPINTVL", 10),
                     ("TCP_KEEPCNT", 6)):
        if hasattr(socket, opt):
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                getattr(socket, opt), val)
            except OSError:
                pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        try:
            chunk = sock.recv(n - len(out))
        except OSError as e:
            raise DMLCError(f"rabit: peer link lost ({e})") from e
        if not chunk:
            raise DMLCError("rabit: peer closed connection")
        out += chunk
    return bytes(out)


class RabitContext:
    """Worker-side rendezvous + collectives."""

    def __init__(self, tracker_uri: str, tracker_port: int,
                 jobid: Optional[str] = None, recover: bool = False,
                 connect_timeout: float = 60.0, connect_links: bool = True,
                 recover_timeout: float = 120.0,
                 heartbeat_interval: Optional[float] = None,
                 telemetry_interval: Optional[float] = None):
        self.tracker_addr = (tracker_uri, tracker_port)
        self.jobid = jobid or f"job-{os.getpid()}-{socket.gethostname()}"
        self.connect_timeout = connect_timeout
        self.recover_timeout = recover_timeout
        # long backstop recv timeout on PEER links: normally a dead peer is
        # detected via the tracker reset's shutdown(SHUT_RDWR), but if the
        # tracker itself is gone a fully-unbounded recv hangs the collective
        # forever.  Sized well past recover_timeout so a slow-but-alive peer
        # (an elastic-reborn rank redoes its epoch) is never misdiagnosed;
        # DMLC_PEER_RECV_TIMEOUT tunes it, <= 0 restores unbounded recv.
        # A malformed value falls back to the default — worker boot must
        # not crash over an env typo.
        try:
            t = float(get_env("DMLC_PEER_RECV_TIMEOUT",
                              2.0 * recover_timeout))
        except (TypeError, ValueError):
            log_warning("rabit: bad DMLC_PEER_RECV_TIMEOUT=%r; using "
                        "default %.0fs",
                        get_env("DMLC_PEER_RECV_TIMEOUT", None),
                        2.0 * recover_timeout)
            t = 2.0 * recover_timeout
        self.peer_recv_timeout: Optional[float] = None if t <= 0 else t
        # listener for peer links
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(16)
        self._listen_port = self._listener.getsockname()[1]
        self._peer_socks: Dict[int, socket.socket] = {}
        self._sock_gen: Dict[int, int] = {}
        # populated by _register's reply; must EXIST before the accept
        # thread starts — a tracker reset_links push can race ahead of
        # the registration reply and must not kill the accept loop
        self._addresses: Dict[int, Tuple[str, int]] = {}
        self._peer_lock = threading.Lock()
        self._reset_event = threading.Event()
        self._target_gen = 0
        self._seq = 0  # collective sequence number (frame guard)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accepting = True
        self._accept_thread.start()
        self._register(recover)
        # liveness beats to the tracker (cmd=heartbeat) feed its
        # dead-worker monitor; a failed beat is the tracker's problem to
        # notice, never this worker's reason to die.  0 disables.
        if heartbeat_interval is None:
            heartbeat_interval = get_env("DMLC_HEARTBEAT_INTERVAL", 5.0)
        self.heartbeat_interval = float(heartbeat_interval)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if self.heartbeat_interval > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, name="rabit-heartbeat",
                daemon=True)
            self._hb_thread.start()
        # fleet telemetry: push this process's registry state to the
        # tracker (cmd=telemetry) on a cadence; the tracker merges the
        # per-rank states into its /metrics.  0 (the default) disables.
        if telemetry_interval is None:
            telemetry_interval = get_env("DMLC_TELEMETRY_INTERVAL", 0.0)
        self.telemetry_interval = float(telemetry_interval)
        self._tel_stop = threading.Event()
        self._tel_thread: Optional[threading.Thread] = None
        if self.telemetry_interval > 0:
            self._tel_thread = threading.Thread(
                target=self._telemetry_loop, name="rabit-telemetry",
                daemon=True)
            self._tel_thread.start()
        if connect_links:
            self._connect_links()

    @classmethod
    def from_env(cls, **kw) -> "RabitContext":
        """Bootstrap from the DMLC_* env contract (reference `local.py:21-27`).
        ``DMLC_CONNECT_TIMEOUT``/``DMLC_RECOVER_TIMEOUT`` (seconds) tune the
        link/recovery deadlines without code changes."""
        uri = get_env("DMLC_TRACKER_URI", "127.0.0.1")
        port = get_env("DMLC_TRACKER_PORT", 9091)
        jobid = get_env("DMLC_TASK_ID", None)
        attempt = get_env("DMLC_NUM_ATTEMPT", 0)
        kw.setdefault("connect_timeout",
                      get_env("DMLC_CONNECT_TIMEOUT", 60.0))
        kw.setdefault("recover_timeout",
                      get_env("DMLC_RECOVER_TIMEOUT", 120.0))
        return cls(uri, port, jobid=jobid, recover=attempt > 0, **kw)

    # -- rendezvous --
    def _register(self, recover: bool) -> None:
        sock = socket.create_connection(self.tracker_addr,
                                        timeout=self.connect_timeout)
        send_json(sock, {"cmd": "recover" if recover else "start",
                         "jobid": self.jobid, "port": self._listen_port})
        f = sock.makefile("r")
        sock.settimeout(self.connect_timeout)
        reply = recv_json(f)
        sock.close()
        if reply is not None and "error" in reply:
            raise DMLCError(f"rabit: tracker rejected registration: "
                            f"{reply['error']}")
        if reply is None or "rank" not in reply:
            raise DMLCError(f"rabit: bad tracker reply {reply!r}")
        self.rank: int = reply["rank"]
        self.world_size: int = reply["world"]
        self.parent: int = reply["parent"]
        self.children: List[int] = reply["children"]
        self.ring_prev: int = reply["ring_prev"]
        self.ring_next: int = reply["ring_next"]
        self.generation: int = reply.get("generation", 0)
        self._apply_topology(self.generation,
                             {int(k): tuple(v)
                              for k, v in reply["addresses"].items()})
        # every log record from this process now carries its rank
        set_log_context(rank=self.rank)

    def _apply_topology(self, gen: int,
                        addresses: Dict[int, Tuple[str, int]]) -> None:
        """Apply a rendezvous reply's topology under the peer lock.

        The accept thread is live before registration finishes, so a
        tracker ``reset_links`` push can interleave with the reply;
        ``_handle_ctrl`` mutates ``_target_gen``/``_addresses`` under
        ``_peer_lock`` and this must too — and must never roll a newer
        pushed topology back to the reply's older one."""
        with self._peer_lock:
            if gen >= self._target_gen:
                self._target_gen = gen
                self._addresses = dict(addresses)
            else:
                # a reset_links push raced ahead of this reply: keep the
                # newer pushed addresses, only fill ranks it left unset
                for r, a in addresses.items():
                    self._addresses.setdefault(r, a)

    # -- link management --
    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                head = _recv_exact(conn, 8)
                (peer_rank,) = struct.unpack("<q", head)
                if peer_rank == _CTRL_RANK:
                    self._handle_ctrl(conn)
                    continue
                (gen,) = struct.unpack("<q", _recv_exact(conn, 8))
                _enable_keepalive(conn)
                conn.settimeout(self.peer_recv_timeout)  # same backstop
                # as dial-direction links (see _dial)
                with self._peer_lock:
                    old = self._peer_socks.get(peer_rank)
                    if old is not None:
                        if self._sock_gen.get(peer_rank, -1) > gen:
                            # a stale dial arriving after a newer link was
                            # already established: reject it
                            conn.close()
                            continue
                        try:
                            old.close()
                        except OSError:
                            pass
                    self._peer_socks[peer_rank] = conn
                    self._sock_gen[peer_rank] = gen
            except (DMLCError, OSError, struct.error):
                # a bad handshake (reset, scanner, garbage) must never kill
                # the accept thread — later peers still need to register
                try:
                    conn.close()
                except OSError:
                    pass

    def _handle_ctrl(self, conn: socket.socket) -> None:
        """Tracker control message after the -2 handshake: one JSON line."""
        try:
            msg = recv_json(conn.makefile("r"))
        finally:
            try:
                conn.close()
            except OSError:
                pass
        if not msg or msg.get("cmd") != "reset_links":
            return
        gen = int(msg["generation"])
        addrs = {int(k): tuple(v) for k, v in msg.get("addresses", {}).items()}
        with self._peer_lock:
            if gen <= self._target_gen:
                return
            self._target_gen = gen
            # refresh neighbor addresses (restarted peers moved ports)
            for r in list(self._addresses):
                if r in addrs:
                    self._addresses[r] = addrs[r]
            # drop every pre-reset socket — shutdown(SHUT_RDWR) first, which
            # (unlike close alone) interrupts a recv blocked in another
            # thread with EOF/error; guarantees no stale half-blob survives
            # into the repaired topology
            for r, s in list(self._peer_socks.items()):
                if self._sock_gen.get(r, -1) < gen:
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        s.close()
                    except OSError:
                        pass
                    del self._peer_socks[r]
                    self._sock_gen.pop(r, None)
        log_warning("rabit rank %d: link reset to generation %d", self.rank, gen)
        self._reset_event.set()

    def _connect_links(self) -> None:
        """Dial peers with rank < ours; accept from ranks > ours (a
        deterministic direction avoids double links)."""
        deadline = time.monotonic() + self.connect_timeout
        gen = self.generation
        needed = set(self._addresses)
        for peer in sorted(needed):
            if peer < self.rank:
                with self._peer_lock:
                    have = (peer in self._peer_socks
                            and self._sock_gen.get(peer, -1) >= gen)
                if not have:
                    sock = self._dial(peer, deadline, gen)
                    with self._peer_lock:
                        self._peer_socks[peer] = sock
                        self._sock_gen[peer] = gen
        # wait for inbound from higher ranks
        higher = {p for p in needed if p > self.rank}
        while True:
            with self._peer_lock:
                missing = {p for p in higher
                           if p not in self._peer_socks
                           or self._sock_gen.get(p, -1) < gen}
            if not missing:
                break
            if time.monotonic() > deadline:
                raise DMLCError(f"rabit rank {self.rank}: peers {missing} "
                                f"never connected")
            time.sleep(0.01)

    def _dial(self, peer: int, deadline: float, gen: int) -> socket.socket:
        host, port = self._addresses[peer]
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                sock = socket.create_connection((host, port), timeout=5.0)
                # the 5s budget is for CONNECTING only — left on the
                # socket it becomes a 5s recv timeout that misdiagnoses a
                # slow peer as dead (an elastic-reborn rank redoes a whole
                # epoch before its first collective while survivors block
                # in theirs).  Peer DEATH is detected by the tracker
                # reset's shutdown(SHUT_RDWR), which interrupts a blocked
                # recv (see _handle_ctrl); peer_recv_timeout is the long
                # env-tunable backstop for when the tracker is gone too —
                # a timeout flows the same OSError → "peer link lost" →
                # recovery path as a closed link.  Accepted sockets get
                # the identical setting in _accept_loop, so both link
                # directions behave the same
                sock.settimeout(self.peer_recv_timeout)
                _enable_keepalive(sock)
                send_all(sock, struct.pack("<qq", self.rank, gen))
                return sock
            except OSError as e:
                last_err = e
                time.sleep(0.05)
        raise DMLCError(f"rabit rank {self.rank}: cannot reach peer {peer} "
                        f"at {host}:{port}: {last_err}")

    def _sock_to(self, peer: int) -> socket.socket:
        with self._peer_lock:
            sock = self._peer_socks.get(peer)
        if sock is None:
            raise DMLCError(f"rabit rank {self.rank}: no link to {peer}")
        return sock

    def _ensure_links(self) -> None:
        """Repair links when a tracker reset moved the target generation.
        Loops: a newer reset arriving during a repair triggers another
        round (the event is cleared BEFORE the target is read, so a
        concurrent notification is never lost)."""
        while True:
            self._reset_event.clear()
            with self._peer_lock:
                target = self._target_gen
            if target <= self.generation:
                return
            self.generation = target
            self._connect_links()
            log_info("rabit rank %d: links repaired at generation %d",
                     self.rank, target)

    def _with_recovery(self, fn):
        """Run a collective; on link failure wait for the tracker's reset,
        repair links, and retry from local inputs.  Safe because a reset
        closes *every* worker's sockets: an aborted attempt leaves no bytes
        behind, and no worker can have completed the op (the crashed rank's
        contribution is required globally), so all workers re-enter the same
        op — guarded by the frame sequence number."""
        deadline = time.monotonic() + self.recover_timeout
        while True:
            try:
                self._ensure_links()
                return fn()
            except (DMLCError, OSError) as e:
                if "out of sync" in str(e):
                    raise
                if time.monotonic() > deadline:
                    raise
                log_warning("rabit rank %d: collective aborted (%s); awaiting "
                            "link repair", self.rank, e)
                # wait for the tracker's reset notification (the restarted
                # worker must come back up and re-register first); poll the
                # target generation too in case the event was consumed by a
                # concurrent repair round
                while time.monotonic() < deadline:
                    if self._reset_event.wait(timeout=1.0):
                        break
                    with self._peer_lock:
                        if self._target_gen > self.generation:
                            break

    # -- collectives (binary tree: reduce up, broadcast down) --
    def allreduce(self, x: np.ndarray, op: str = "sum") -> np.ndarray:
        fn = _OPS.get(op)
        if fn is None:
            raise DMLCError(f"unknown op {op!r}; have {list(_OPS)}")
        seq = self._seq

        def attempt() -> np.ndarray:
            acc = np.array(x, copy=True)
            for child in self.children:
                contrib = np.frombuffer(_recv_blob(self._sock_to(child), seq),
                                        dtype=acc.dtype).reshape(acc.shape)
                acc = fn(acc, contrib)
            if self.parent >= 0:
                _send_blob(self._sock_to(self.parent), acc.tobytes(), seq)
                acc = np.frombuffer(_recv_blob(self._sock_to(self.parent), seq),
                                    dtype=acc.dtype).reshape(acc.shape)
            for child in self.children:
                _send_blob(self._sock_to(child), acc.tobytes(), seq)
            if not acc.flags.writeable:
                # frombuffer views are read-only; callers mutate results in
                # place (the reference rabit Allreduce is in-place by contract)
                acc = acc.copy()
            return acc

        out = self._with_recovery(attempt)
        self._seq = seq + 1
        return out

    def broadcast(self, obj: Any, root: int = 0) -> Any:
        """Tree broadcast of an arbitrary picklable object from ``root``.

        Same two-phase traffic pattern as allreduce (climb then descend) with
        a 'first non-empty wins' combiner, so arbitrary roots need no special
        routing and every queued blob is always consumed."""
        if self.world_size == 1:
            return obj
        seq = self._seq

        def attempt() -> bytes:
            payload = pack_obj(obj) if self.rank == root else b""
            for child in self.children:
                contrib = _recv_blob(self._sock_to(child), seq)
                if contrib and not payload:
                    payload = contrib
            if self.parent >= 0:
                _send_blob(self._sock_to(self.parent), payload, seq)
                payload = _recv_blob(self._sock_to(self.parent), seq)
            for child in self.children:
                _send_blob(self._sock_to(child), payload, seq)
            return payload

        payload = self._with_recovery(attempt)
        self._seq = seq + 1
        if not payload:
            raise DMLCError(f"broadcast: no payload reached rank {self.rank}")
        return unpack_obj(payload)

    def allgather(self, x: np.ndarray) -> np.ndarray:
        """Gather per-rank arrays to all (via allreduce of a one-hot stack)."""
        x = np.asarray(x)
        stack = np.zeros((self.world_size,) + x.shape, x.dtype)
        stack[self.rank] = x
        return self.allreduce(stack, "sum")

    # -- checkpoint API (rabit CheckPoint/LoadCheckPoint/VersionNumber) --
    def _ckpt_path(self) -> str:
        import tempfile
        d = get_env("DMLC_CHECKPOINT_DIR", tempfile.gettempdir())
        # key by tracker address as well as jobid: tracker ports are
        # ephemeral per job, so a later job with the same task ids cannot
        # resurrect a stale checkpoint from a previous run
        tag = f"{self.tracker_addr[0]}_{self.tracker_addr[1]}".replace(
            os.sep, "_")
        return os.path.join(d, f"rabit_ckpt_{tag}_{self.jobid}.pkl")

    def checkpoint(self, state: Any) -> None:
        """Persist app state + the collective sequence number, so a restarted
        worker resumes in lock-step with survivors (rabit's ``CheckPoint``;
        state recovery itself is local-disk here — the reference's
        peer-to-peer ring recovery is downstream rabit, SURVEY §5)."""
        payload = pack_obj({"seq": self._seq, "state": state,
                                "version": getattr(self, "_version", 0) + 1})
        self._version = getattr(self, "_version", 0) + 1
        tmp = self._ckpt_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self._ckpt_path())

    def load_checkpoint(self) -> Optional[Any]:
        """Restore state saved by :meth:`checkpoint`; fast-forwards the
        collective sequence counter (rabit's ``LoadCheckPoint``).  Returns
        None when no checkpoint exists (fresh start)."""
        try:
            with open(self._ckpt_path(), "rb") as f:
                saved = unpack_obj(f.read())
        except (OSError, pickle.UnpicklingError):
            return None
        self._seq = saved["seq"]
        self._version = saved.get("version", 0)
        return saved["state"]

    @property
    def version_number(self) -> int:
        return getattr(self, "_version", 0)

    @property
    def seq(self) -> int:
        """Collective sequence counter — persist it with externally-stored
        state (CheckpointManager over s3://…) so a worker reborn on a
        DIFFERENT host (node replacement: local disk gone, so
        :meth:`load_checkpoint` has nothing) can :meth:`resume_seq` into
        lock-step with survivors."""
        return self._seq

    def resume_seq(self, seq: int) -> None:
        """Fast-forward the sequence counter after restoring app state from
        a durable checkpoint — the external-store analog of
        :meth:`load_checkpoint`'s seq restore.  Only valid before the first
        post-restart collective; without it a reborn worker's first frame
        trips the survivors' out-of-sync guard and the whole cohort falls
        back to checkpoint-restart (safe, but a full-job bounce)."""
        if self._seq != 0:
            raise DMLCError(
                f"resume_seq after {self._seq} collectives — call it "
                f"immediately after restore, before any allreduce")
        self._seq = int(seq)

    def _heartbeat_loop(self) -> None:
        from ..utils.metrics import metrics
        while not self._hb_stop.wait(jittered(self.heartbeat_interval)):
            try:
                self._tracker_cmd({"cmd": "heartbeat", "jobid": self.jobid})
            except OSError:
                # tracker briefly unreachable — beats are best-effort
                metrics.counter("rabit.heartbeat.failures").add(1)

    # -- fleet telemetry --
    def push_telemetry(self) -> None:
        """Push this process's full registry state (mergeable form — see
        ``MetricsRegistry.state``) to the tracker, tagged with our rank.
        Device-memory/live-buffer gauges are refreshed first so the fleet
        view carries current XLA memory state (no-op without JAX)."""
        from ..telemetry.xla_introspect import sample_memory
        from ..utils.metrics import metrics
        try:
            sample_memory()
        except Exception:   # sampling must never break the push
            pass
        self._tracker_cmd({"cmd": "telemetry", "jobid": self.jobid,
                           "rank": self.rank, "state": metrics.state()})

    def _telemetry_loop(self) -> None:
        from ..utils.metrics import metrics
        while not self._tel_stop.wait(jittered(self.telemetry_interval)):
            try:
                self.push_telemetry()
            except OSError:
                metrics.counter("rabit.telemetry.failures").add(1)

    # -- misc rabit API --
    def tracker_print(self, msg: str) -> None:
        self._tracker_cmd({"cmd": "print", "msg": msg})

    def shutdown(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        self._tel_stop.set()
        if self._tel_thread is not None:
            self._tel_thread.join(timeout=2.0)
            try:  # final push so the fleet view reflects the full run
                self.push_telemetry()
            except OSError:
                pass
        self._tracker_cmd({"cmd": "shutdown", "jobid": self.jobid})
        try:  # clean exit: the recovery checkpoint is no longer needed
            os.unlink(self._ckpt_path())
        except OSError:
            pass
        self._accepting = False
        try:
            self._listener.close()
        except OSError:
            pass
        with self._peer_lock:
            for sock in self._peer_socks.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._peer_socks.clear()
            self._sock_gen.clear()

    def _tracker_cmd(self, obj: dict) -> None:
        sock = socket.create_connection(self.tracker_addr, timeout=10.0)
        send_json(sock, obj)
        sock.close()

    def __enter__(self) -> "RabitContext":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
