"""Pipeline parallelism over a named 'pp' mesh axis (GPipe-style).

The reference has no pipeline-across-devices concept — its pipelining is
producer/consumer prefetch threads inside one process (SURVEY §2.5
"Parallelism strategies", `threadediter.h:46`).  On a TPU mesh the same
capability — stages of a computation running concurrently on different
hardware — is expressed as a schedule over a mesh axis: device *s* along
'pp' owns stage *s*'s parameters, microbatches stream through the stages,
and stage hand-offs ride ICI via ``lax.ppermute``.

Schedule.  Fill-and-drain (GPipe): with S stages and M microbatches the
scan runs ``T = M + S − 1`` ticks; at tick *t* stage *s* processes
microbatch ``t − s`` (bubble ticks compute on zeros and are masked out of
the collected output).  Everything is a single ``lax.scan`` inside one
``shard_map`` — no Python-level per-tick dispatch, one compiled program.

Contract.  ``stage_fn(stage_params, x) -> y`` must preserve the microbatch
shape (uniform-width tower; put input/output projections outside the
pipeline).  ``stage_params`` leaves are stacked on a leading stage axis of
size S and sharded ``P('pp')``, so each device holds exactly its stage's
slice — the parameter-memory win pipeline parallelism exists for.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from ..utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_pipeline", "split_microbatches", "stack_stage_params",
           "stage_sharding"]


def split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] → [M, B/M, ...] (B must divide evenly)."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by M={num_microbatches}")
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])


def stack_stage_params(per_stage: list) -> dict:
    """[{leaf: array}, ...] per stage → {leaf: array[S, ...]} stacked."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def make_pipeline(mesh: Mesh, axis: str,
                  stage_fn: Callable) -> Callable:
    """Build ``run(stage_params, xs) -> ys``: microbatches ``xs[M, mb, F]``
    through S = mesh.shape[axis] stages of ``stage_fn``.

    Returns outputs ``[M, mb, F]`` replicated over the axis.  Stage
    parameters are consumed sharded ``P(axis)`` on their stacked leading
    axis; inputs/outputs are replicated (shard the batch over 'dp', not
    'pp' — the two axes compose in a 2-D mesh).
    """
    num_stages = mesh.shape[axis]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P()), out_specs=P())
    def run(stage_params, xs):
        # my slice of the stacked stage axis has length 1 — drop it
        params_me = jax.tree.map(lambda a: a[0], stage_params)
        s = jax.lax.axis_index(axis)
        num_m = xs.shape[0]
        ticks = num_m + num_stages - 1
        # stage i hands its activation to stage i+1; the last stage's
        # output leaves the ring (collected below), stage 0's input comes
        # from the microbatch stream
        perm = [(i, i + 1) for i in range(num_stages - 1)]

        def tick(carry, t):
            cur, outs = carry
            y = stage_fn(params_me, cur)
            handed = jax.lax.ppermute(y, axis, perm)
            inject = xs[jnp.clip(t + 1, 0, num_m - 1)]
            cur = jnp.where(s == 0, inject, handed)
            # the last stage finished microbatch t-(S-1) this tick
            oidx = t - (num_stages - 1)
            ok = jnp.logical_and(oidx >= 0, s == num_stages - 1)
            ci = jnp.clip(oidx, 0, num_m - 1)
            outs = outs.at[ci].set(jnp.where(ok, y, outs[ci]))
            return (cur, outs), None

        cur0 = jnp.where(s == 0, xs[0], jnp.zeros_like(xs[0]))
        # the carry becomes device-varying over 'pp' inside the loop, so
        # the initial value must carry the same varying-manual-axes type
        zeros = jnp.zeros_like(xs)
        if hasattr(jax.lax, "pcast"):
            outs0 = jax.lax.pcast(zeros, (axis,), to="varying")
        else:
            # pre-varying-types jax has no manual-axes type distinction;
            # the untyped zeros carry is already correct there
            outs0 = zeros
        (_, outs), _ = jax.lax.scan(tick, (cur0, outs0),
                                    jnp.arange(ticks))
        # only the last stage holds real outputs; psum replicates them so
        # the caller sees an ordinary (unsharded-over-pp) result
        return jax.lax.psum(
            jnp.where(s == num_stages - 1, outs, jnp.zeros_like(outs)),
            axis)

    return run


def stage_sharding(mesh: Mesh, axis: str = "pp") -> NamedSharding:
    """Sharding for stacked stage params (leading stage axis over 'pp')."""
    return NamedSharding(mesh, P(axis))
