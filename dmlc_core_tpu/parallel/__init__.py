"""Distributed layer: device-mesh collectives, rendezvous tracker, rabit
client, cluster launchers (reference ``tracker/`` — SURVEY §2.5, §5.8)."""

from .mesh import (make_mesh, parse_mesh_spec, data_parallel_mesh,  # noqa: F401
                   process_mesh_info, row_partition, remap_rows, row_owners)
from .collectives import (allreduce, broadcast, allgather,  # noqa: F401
                          reduce_scatter, all_to_all, MeshCollectives)
from .tracker import (RabitTracker, PSTracker, compute_tree,  # noqa: F401
                      compute_ring)
from .rabit import RabitContext  # noqa: F401
from .reshard import (StateHandle, ReshardStats, HostSnapshot,  # noqa: F401
                      snapshot_tree, redistribute)
from .elastic import ElasticJaxMesh, ResyncResult  # noqa: F401

__all__ = [
    "PSTracker",
    "make_mesh", "parse_mesh_spec", "data_parallel_mesh", "process_mesh_info",
    "row_partition", "remap_rows", "row_owners",
    "allreduce", "broadcast", "allgather", "reduce_scatter", "all_to_all",
    "MeshCollectives",
    "RabitTracker", "compute_tree", "compute_ring", "RabitContext",
    "StateHandle", "ReshardStats", "HostSnapshot", "snapshot_tree",
    "redistribute",
    "ElasticJaxMesh", "ResyncResult",
]
