"""Rabit-compatible collective API lowered to XLA mesh collectives.

The reference ecosystem's collective surface is rabit's ``Allreduce(op)`` /
``Broadcast(root)`` executed over tracker-brokered TCP trees (SURVEY §2.5,
`tracker.py:166-252`).  On TPU the same API lowers to ``lax.psum``-family ops
over ICI/DCN — XLA routes them; the tree/ring computation disappears.

Two tiers:

* **In-jit** (:func:`allreduce`, :func:`broadcast`, :func:`allgather`):
  shard_map-based, for use *inside* jitted step functions over a Mesh.
* **Eager host-level** (:class:`MeshCollectives`): one-call collectives on
  full arrays — the literal rabit API (``allreduce(x, op='sum')``), backed by
  a tiny jitted program per (shape, op).

The socket-based host collective for non-JAX processes (the tracker data
path) lives in :mod:`dmlc_core_tpu.parallel.rabit`.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from ..utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import DMLCError, check

__all__ = ["allreduce", "broadcast", "allgather", "reduce_scatter",
           "all_to_all", "MeshCollectives", "OPS"]

OPS: Dict[str, Callable] = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


def allreduce(x: jax.Array, axis_name: str, op: str = "sum") -> jax.Array:
    """In-jit allreduce over a mesh axis (use under shard_map/jit)."""
    fn = OPS.get(op)
    if fn is None:
        raise DMLCError(f"unknown allreduce op {op!r}; have {list(OPS)}")
    return fn(x, axis_name)


def broadcast(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """In-jit broadcast from mesh coordinate ``root`` along ``axis_name``."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def allgather(x: jax.Array, axis_name: str, axis: int = 0,
              tiled: bool = True) -> jax.Array:
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: str, axis: int = 0) -> jax.Array:
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def all_to_all(x: jax.Array, axis_name: str, split_axis: int = 0,
               concat_axis: int = 0, tiled: bool = True) -> jax.Array:
    """In-jit all-to-all over a mesh axis: split ``split_axis`` into
    ``world`` chunks, send chunk *d* to coordinate *d*, concatenate the
    received chunks along ``concat_axis``.  This is the mapped-primitive
    lowering of the sharded-embedding exchange (DrJAX's mapped
    ``all_to_all``, PAPERS.md: arxiv 2403.07128): when table shards and
    batch ids live on one process's mesh, the same shuffle the
    cross-process exchange does over TCP lowers to a single XLA
    collective over ICI."""
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


class MeshCollectives:
    """Eager rabit-style collectives over one mesh axis.

    >>> coll = MeshCollectives(mesh, "dp")
    >>> y = coll.allreduce(x)             # sum over the dp axis
    >>> z = coll.broadcast(x, root=0)
    """

    def __init__(self, mesh: Mesh, axis_name: str = "dp"):
        check(axis_name in mesh.axis_names,
              f"axis {axis_name!r} not in mesh {mesh.axis_names}")
        self.mesh = mesh
        self.axis_name = axis_name
        self._cache: Dict[Tuple, Callable] = {}

    @property
    def world_size(self) -> int:
        return self.mesh.shape[self.axis_name]

    def _spec_in(self) -> P:
        # input arrays are sharded on their leading dim over the axis
        return P(self.axis_name)

    def _jitted(self, kind: str, op: str, root: int,
                shape: Tuple[int, ...], dtype) -> Callable:
        key = (kind, op, root, shape, dtype)
        fn = self._cache.get(key)
        if fn is not None:
            return fn
        axis = self.axis_name

        if kind == "allreduce":
            # each rank contributes its row; result identical on all ranks
            def body(x):
                return allreduce(x, axis, op)
        elif kind == "broadcast":
            def body(x):
                return broadcast(x, axis, root)
        elif kind == "allgather":
            def body(x):
                return allgather(x, axis)
        elif kind == "all_to_all":
            # local block is [1, world, ...]: exchange the second axis,
            # then restore the leading layout so rank r's block is the
            # column in[:, r] — i.e. out[d] = in[:, d] globally
            def body(x):
                y = all_to_all(x, axis, split_axis=1, concat_axis=0)
                return jnp.swapaxes(y, 0, 1)
        else:
            raise DMLCError(f"unknown collective {kind!r}")

        out_spec = P() if kind == "allgather" else P(axis)

        def run(stacked):
            return shard_map(body, mesh=self.mesh,
                             in_specs=P(axis), out_specs=out_spec,
                             check_vma=False)(stacked)
        fn = jax.jit(run)
        self._cache[key] = fn
        return fn

    def _stack(self, per_rank: np.ndarray) -> jax.Array:
        """per_rank: [world, ...] array, row r = rank r's contribution."""
        sharding = NamedSharding(self.mesh, P(self.axis_name))
        return jax.device_put(per_rank, sharding)

    def allreduce(self, per_rank: np.ndarray, op: str = "sum") -> np.ndarray:
        """Rabit Allreduce: per_rank[world, ...] → reduced [...] (same on all)."""
        per_rank = np.asarray(per_rank)
        check(per_rank.shape[0] == self.world_size,
              f"leading dim {per_rank.shape[0]} != world {self.world_size}")
        x = self._stack(per_rank)
        fn = self._jitted("allreduce", op, 0, per_rank.shape, per_rank.dtype)
        out = np.asarray(fn(x))
        return out[0]  # all rows identical post-allreduce

    def broadcast(self, per_rank: np.ndarray, root: int = 0) -> np.ndarray:
        per_rank = np.asarray(per_rank)
        x = self._stack(per_rank)
        fn = self._jitted("broadcast", "sum", root, per_rank.shape,
                          per_rank.dtype)
        return np.asarray(fn(x))[0]

    def allgather(self, per_rank: np.ndarray) -> np.ndarray:
        """Returns the full [world, ...] stack on host."""
        per_rank = np.asarray(per_rank)
        x = self._stack(per_rank)
        fn = self._jitted("allgather", "sum", 0, per_rank.shape,
                          per_rank.dtype)
        return np.asarray(fn(x))

    def all_to_all(self, per_rank: np.ndarray) -> np.ndarray:
        """Rabit-style all-to-all: ``per_rank[src, dst, ...]`` (row *src*
        = rank *src*'s outbox, entry *dst* = its chunk for rank *dst*)
        → ``out[dst, src, ...]`` where ``out[d]`` is rank *d*'s inbox —
        ``out[d, s] == per_rank[s, d]``.  One XLA collective; this is the
        in-mesh lowering of the sharded-embedding id/row shuffle."""
        per_rank = np.asarray(per_rank)
        check(per_rank.ndim >= 2
              and per_rank.shape[0] == self.world_size
              and per_rank.shape[1] == self.world_size,
              f"all_to_all wants [world, world, ...], got {per_rank.shape}")
        x = self._stack(per_rank)
        fn = self._jitted("all_to_all", "sum", 0, per_rank.shape,
                          per_rank.dtype)
        return np.asarray(fn(x))