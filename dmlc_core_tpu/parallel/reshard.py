"""Checkpoint-free elastic resharding: live state redistribution on a
generation bump (ROADMAP [scale/elasticity]; PAPERS.md arxiv 2112.01075
portable collective redistribution, arxiv 2403.07128 DrJAX mapreduce
framing).

Before this module, surviving a worker death meant every process reloaded
model + optimizer state from the last checkpoint — minutes of lost work
and a full-fleet I/O stampede per failure, even though the survivors
already held a complete copy of the state between them.  The resharder
turns a generation bump into a data movement problem instead:

1. **snapshot** — before :meth:`ElasticJaxMesh.ensure` tears the data
   plane down, each survivor copies its live pytree shards to host
   memory (:func:`snapshot_tree`; donation-safe, bounded by
   ``DMLC_RESHARD_MAX_BYTES``).  Device arrays die with the backend; the
   host copies do not.
2. **agree** — after the mesh rebuilds at the new generation, the cohort
   agrees on a shard-ownership map over the rabit control plane: every
   rank broadcasts its leaf schema, held row ranges, and a transfer
   address (world broadcast rounds — uniform collective order on every
   rank, so the rabit seq frames stay aligned).
3. **redistribute** — each rank assembles its target shard of every leaf
   from (a) its own host pieces, (b) point-to-point TCP fetches from
   peers that hold the missing row ranges (owners spread round-robin so
   one survivor does not serve the whole reborn rank alone), and only
   then (c) leaf-granular checkpoint reads
   (:meth:`~..utils.checkpoint.CheckpointManager.restore_leaves`) for
   shards NO survivor holds.
4. **verify** — a final allreduce agrees the cohort-wide count of
   unrecoverable ranges; any gap anywhere raises on EVERY rank (a
   half-restored cohort must not train), with a flight-recorder incident
   bundle capturing the failed recovery.

Shard model: leaves are blocks of CONTIGUOUS rows of axis 0 — replicated
leaves are one whole block, row-sharded tables carry ``(start, stop)``
ranges against the global shape (the reference's ``ResetPartition``
contract; ``mesh.row_partition`` computes the target ranges when the
cohort shrinks or grows).  0-d leaves are treated as one row.

Telemetry: ``elastic.reshard_wall_s`` gauge, ``reshard.bytes_moved`` /
``reshard.leaves_from_peers`` / ``reshard.leaves_from_checkpoint``
counters, and a ``reshard`` span with per-phase events so the flight
recorder captures failed recoveries.  ``fault_point("reshard.fetch")``
arms the chaos harness on every peer fetch.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import flight as telflight
from ..telemetry import trace as teltrace
from ..transport import plan as transport_plan
from ..transport.frames import send_all as _send_all
from ..utils import DMLCError, log_info, log_warning
from ..utils.checkpoint import (CheckpointManager, flatten_tree,
                                unflatten_like)
from ..utils.faults import fault_point
from ..utils.metrics import metrics
from ..utils.parameter import env_int, get_env

__all__ = ["StateHandle", "ReshardStats", "HostSnapshot", "snapshot_tree",
           "redistribute"]

_MAGIC = b"DMRS1"
#: rank sentinel for "nobody holds state" in the holder-agreement round
_NOBODY = 1 << 30
#: default host-snapshot budget: 4 GiB (DMLC_RESHARD_MAX_BYTES overrides)
_DEFAULT_BUDGET = 4 << 30


def _rows(shape: Tuple[int, ...]) -> int:
    return int(shape[0]) if shape else 1


def _timeout_s() -> float:
    return float(env_int("DMLC_RESHARD_TIMEOUT_S", 60, minimum=1))


def _apply_sock_buf(sock: socket.socket) -> None:
    """Honor ``DMLC_SOCK_BUF_KB`` (lenient env_int, 0 = kernel default):
    both directions sized, on the transfer server's listener (accepted
    sockets inherit) and on every fetch dial — reshard moves tens of MB
    per connection, where default buffers leave WAN bandwidth idle."""
    kb = env_int("DMLC_SOCK_BUF_KB", 0, minimum=0)
    if kb <= 0:
        return
    for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, kb * 1024)
        except OSError:
            pass    # the kernel clamps or refuses; either is fine


# ---------------------------------------------------------------------------
# host snapshot
# ---------------------------------------------------------------------------

class HostSnapshot:
    """Host-side copies of the shards this rank holds.

    ``pieces[path]`` is a list of ``(start, stop, array)`` blocks covering
    row ranges ``[start, stop)`` of axis 0 of the GLOBAL leaf;
    ``schema[path]`` is ``(global_shape, dtype_str)``.  A replicated leaf
    is one whole block; 0-d leaves are stored as shape ``(1,)`` blocks
    with a ``()`` global shape so slicing stays uniform."""

    def __init__(self) -> None:
        self.pieces: Dict[str, List[Tuple[int, int, np.ndarray]]] = {}
        self.schema: Dict[str, Tuple[Tuple[int, ...], str]] = {}
        self.nbytes = 0

    def add(self, path: str, arr: np.ndarray, *, start: int = 0,
            global_rows: Optional[int] = None) -> None:
        """Record a held block: rows ``[start, start+len)`` of a leaf whose
        global leading dim is ``global_rows`` (default: this block ends
        the leaf — i.e. a whole replicated leaf when ``start`` is 0)."""
        # check ndim BEFORE ascontiguousarray: its contract is "at least
        # 1-d", which would silently turn a 0-d leaf into shape (1,)
        if arr.ndim == 0:
            gshape: Tuple[int, ...] = ()
            arr = np.ascontiguousarray(arr).reshape((1,))
            start, stop = 0, 1
        else:
            arr = np.ascontiguousarray(arr)
            stop = start + arr.shape[0]
            grows = stop if global_rows is None else int(global_rows)
            gshape = (grows,) + tuple(arr.shape[1:])
        prev = self.schema.get(path)
        if prev is not None and prev != (gshape, str(arr.dtype)):
            raise DMLCError(f"snapshot schema conflict for {path!r}: "
                            f"{prev} vs {(gshape, str(arr.dtype))}")
        self.schema[path] = (gshape, str(arr.dtype))
        self.pieces.setdefault(path, []).append((int(start), int(stop), arr))
        self.nbytes += arr.nbytes


def snapshot_tree(tree: Any, *, max_bytes: Optional[int] = None
                  ) -> Optional[HostSnapshot]:
    """Copy a live pytree's array leaves to host memory as whole
    (replicated) blocks.  Copies are taken eagerly so donation or a
    backend teardown cannot invalidate them.  Returns None — "this rank
    holds nothing" — when the state exceeds the ``DMLC_RESHARD_MAX_BYTES``
    budget, demoting recovery to the checkpoint path instead of OOMing
    the host mid-teardown."""
    budget = (env_int("DMLC_RESHARD_MAX_BYTES", _DEFAULT_BUDGET, minimum=0)
              if max_bytes is None else int(max_bytes))
    snap = HostSnapshot()
    for path, arr in flatten_tree(tree).items():
        snap.add(path, np.array(arr, copy=True))
        if snap.nbytes > budget:
            metrics.counter("reshard.snapshot_skipped").add(1)
            log_warning("reshard: state exceeds snapshot budget "
                        "(%d > %d bytes) — this rank will not serve "
                        "shards; recovery falls back to checkpoint",
                        snap.nbytes, budget)
            return None
    return snap


# ---------------------------------------------------------------------------
# state handle (what ElasticJaxMesh snapshots and restores)
# ---------------------------------------------------------------------------

class StateHandle:
    """Live-state registration for :class:`~.elastic.ElasticJaxMesh`.

    ``get_state()`` returns the pytree to preserve across a rebuild (or
    None when this rank currently holds nothing — e.g. a reborn process);
    ``set_state(state)`` — optional — receives the restored tree after the
    rebuild (callers may instead read ``resync()``'s ``.state``).

    ``template`` (pytree or zero-arg callable) supplies the container
    structure for the restored tree; without it the restore is the flat
    ``{path: array}`` mapping.  ``plan(path, global_shape) -> (start,
    stop) | None`` maps each leaf to this rank's target row range (None =
    whole leaf, the replicated default).  ``checkpoint`` (manager or
    directory) is the last-resort source for shards no survivor holds.

    ``snapshot`` — optional zero-arg callable returning a ready
    :class:`HostSnapshot` (or None for "holds nothing") — replaces the
    default ``snapshot_tree(get_state())`` path.  It exists for state
    that is *already row-sharded in host memory* (the sharded embedding
    table): such owners record ranged blocks via ``HostSnapshot.add(...,
    start=, global_rows=)`` — including replica blocks of peers' shards —
    which the whole-leaf ``snapshot_tree`` copy cannot express.

    COLLECTIVE CONTRACT: register the handle at the same point relative
    to control-plane collectives on every rank — the redistribute rounds
    run inside ``ensure()`` and must execute uniformly cohort-wide.
    """

    def __init__(self, get_state: Callable[[], Any],
                 set_state: Optional[Callable[[Any], None]] = None, *,
                 template: Any = None,
                 plan: Optional[Callable[[str, Tuple[int, ...]],
                                         Optional[Tuple[int, int]]]] = None,
                 checkpoint: Any = None,
                 checkpoint_step: Optional[int] = None,
                 snapshot: Optional[Callable[[], Optional["HostSnapshot"]]]
                 = None) -> None:
        self.get_state = get_state
        self.set_state = set_state
        self.template = template
        self.plan = plan
        self.checkpoint = checkpoint
        self.checkpoint_step = checkpoint_step
        self.snapshot = snapshot

    def resolve_template(self) -> Any:
        t = self.template
        return t() if callable(t) else t

    def resolve_checkpoint(self) -> Optional[CheckpointManager]:
        c = self.checkpoint
        if c is None:
            return None
        return c if isinstance(c, CheckpointManager) else CheckpointManager(
            str(c))


class ReshardStats:
    """Outcome of one redistribute round (attached to ``resync()``)."""

    __slots__ = ("wall_s", "bytes_moved", "leaves_from_peers",
                 "leaves_local", "leaves_from_checkpoint", "world")

    def __init__(self) -> None:
        self.wall_s = 0.0
        self.bytes_moved = 0
        self.leaves_from_peers = 0
        self.leaves_local = 0
        self.leaves_from_checkpoint = 0
        self.world = 0

    def as_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self) -> str:
        body = ", ".join(f"{k}={getattr(self, k)}" for k in self.__slots__)
        return f"ReshardStats({body})"


# ---------------------------------------------------------------------------
# wire helpers (point-to-point shard transfer)
# ---------------------------------------------------------------------------

def _recv_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket — recv_into straight into the target
    buffer (an assembled leaf's own memory on the fetch path), no
    intermediate bytes objects."""
    while view.nbytes:
        got = sock.recv_into(view)
        if not got:
            raise DMLCError("reshard transfer stream truncated")
        view = view[got:]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_into(sock, memoryview(buf))
    return bytes(buf)


def _my_host(ctx) -> str:
    """The address peers can dial for shard fetches: explicit override,
    else the interface that routes to the tracker (the UDP-connect trick
    — nothing is sent), else loopback."""
    override = get_env("DMLC_RESHARD_HOST", "").strip()
    if override:
        return override
    try:
        tracker = getattr(ctx, "tracker_addr", None)
        if tracker:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((tracker[0], int(tracker[1])))
                return s.getsockname()[0]
            finally:
                s.close()
    except OSError:
        pass
    return "127.0.0.1"


class _XferServer:
    """One-generation shard server: answers ``(path, start, stop)``
    requests from the local :class:`HostSnapshot` until closed.  Requests
    are sliced from a single held block (the fetch planner never splits a
    request across blocks), so a miss means the peer's ownership map was
    stale — answered with a miss byte, not a hang."""

    def __init__(self, snap: HostSnapshot) -> None:
        self._snap = snap
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        _apply_sock_buf(self._sock)
        self._sock.bind(("", 0))
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._threads: List[threading.Thread] = []
        self._accept = threading.Thread(target=self._accept_loop,
                                        name="reshard-xfer", daemon=True)
        self._accept.start()

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_one, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(_timeout_s())
                magic = _recv_exact(conn, len(_MAGIC))
                if magic != _MAGIC:
                    return
                (nreq,) = struct.unpack("<I", _recv_exact(conn, 4))
                req = json.loads(_recv_exact(conn, nreq).decode())
                path = req["path"]
                start, stop = int(req["start"]), int(req["stop"])
                block = None
                for (s, e, arr) in self._snap.pieces.get(path, ()):
                    if s <= start and stop <= e:
                        block = arr[start - s:stop - s]
                        break
                if block is None:
                    _send_all(conn, b"\x00")
                    return
                block = np.ascontiguousarray(block)
                meta = json.dumps({"dtype": str(block.dtype),
                                   "shape": list(block.shape)}).encode()
                _send_all(conn, b"\x01" + struct.pack("<I", len(meta))
                          + meta + struct.pack("<Q", block.nbytes))
                # send straight from the snapshot block's buffer — a
                # .tobytes() here would copy each served shard once more
                _send_all(conn, memoryview(block).cast("B"))
        except (OSError, ValueError, KeyError, DMLCError):
            pass        # a broken fetcher retries against another holder

    def close(self) -> None:
        if self._stop:
            return
        self._stop = True
        try:
            # wake a blocked accept() NOW instead of waiting out its 0.2s
            # poll — close() sits on every rank's redistribute exit path
            with socket.create_connection(("127.0.0.1", self.port),
                                          timeout=0.5):
                pass
        except OSError:
            pass
        self._accept.join(timeout=2.0)
        try:
            self._sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=2.0)


def _fetch(addr: Tuple[str, int], path: str, start: int, stop: int
           ) -> np.ndarray:
    """Dial a peer's transfer server for rows [start, stop) of a leaf."""
    fault_point("reshard.fetch")
    timeout = _timeout_s()
    with socket.create_connection(addr, timeout=timeout) as s:
        s.settimeout(timeout)
        _apply_sock_buf(s)
        req = json.dumps({"path": path, "start": start,
                          "stop": stop}).encode()
        _send_all(s, _MAGIC + struct.pack("<I", len(req)) + req)
        status = _recv_exact(s, 1)
        if status != b"\x01":
            raise DMLCError(f"peer {addr} does not hold {path!r} "
                            f"[{start}:{stop})")
        (nmeta,) = struct.unpack("<I", _recv_exact(s, 4))
        meta = json.loads(_recv_exact(s, nmeta).decode())
        (nbytes,) = struct.unpack("<Q", _recv_exact(s, 8))
        out = np.empty(tuple(meta["shape"]), dtype=np.dtype(meta["dtype"]))
        if out.nbytes != nbytes:
            raise DMLCError(f"reshard fetch size mismatch for {path!r}: "
                            f"peer sends {nbytes} bytes, shape/dtype say "
                            f"{out.nbytes}")
        if nbytes:
            # recv_into the destination array itself — no intermediate
            # bytes object, no frombuffer+copy
            _recv_into(s, memoryview(out).cast("B"))
    return out


# ---------------------------------------------------------------------------
# the redistribute protocol
# ---------------------------------------------------------------------------

def _merge_infos(infos: List[Optional[Dict[str, Any]]]):
    """Union the per-rank manifests into (schema, holders, addrs).  A
    schema conflict is a divergence bug — every rank sees the same infos,
    so the raise is uniform cohort-wide."""
    schema: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    holders: Dict[str, List[Tuple[int, int, int]]] = {}
    addrs: Dict[int, Tuple[str, int]] = {}
    for r, info in enumerate(infos):
        if not info:
            continue
        if info.get("addr"):
            addrs[r] = (info["addr"][0], int(info["addr"][1]))
        for path, (gshape, dt) in info["schema"].items():
            entry = (tuple(int(d) for d in gshape), dt)
            if path in schema and schema[path] != entry:
                raise DMLCError(
                    f"reshard: schema conflict for {path!r}: "
                    f"{schema[path]} vs {entry} (rank {r})")
            schema[path] = entry
        for path, ranges in info["holds"].items():
            for s, e in ranges:
                holders.setdefault(path, []).append((r, int(s), int(e)))
    return schema, holders, addrs


def _plan_leaf(target: Tuple[int, int],
               local: List[Tuple[int, int, np.ndarray]],
               remote: List[Tuple[int, int, int]], spread: int):
    """Cover [target) rows from local blocks first, then remote holders,
    and report any gap.  Returns (segments, fetches, gaps) where segments
    is ``[(start, array-or-None placeholder index)]`` ordered by start:
    local slices materialize now, fetches later.  Remote choice among
    equally-covering holders rotates with ``spread`` so one survivor does
    not serve every leaf of a reborn rank."""
    segments: List[Tuple[int, Optional[np.ndarray]]] = []
    fetches: List[Tuple[int, int, int, List[int]]] = []  # start,stop,rank,alts
    gaps: List[Tuple[int, int]] = []
    pos, stop = target
    n = 0
    while pos < stop:
        best_local = None
        for (s, e, arr) in local:
            if s <= pos < e and (best_local is None or e > best_local[1]):
                best_local = (s, e, arr)
        if best_local is not None:
            s, e, arr = best_local
            upto = min(e, stop)
            segments.append((pos, arr[pos - s:upto - s]))
            pos = upto
            continue
        covering = [(r, s, e) for (r, s, e) in remote if s <= pos < e]
        if covering:
            far = max(e for (_, _, e) in covering)
            ties = sorted(r for (r, _, e) in covering if e == far)
            owner = ties[(spread + n) % len(ties)]
            alts = [r for r in ties if r != owner] + sorted(
                r for (r, _, e) in covering if e != far)
            upto = min(far, stop)
            fetches.append((pos, upto, owner, alts))
            segments.append((pos, None))
            pos = upto
            n += 1
            continue
        # uncovered: skip forward to the next held row (or give up)
        nxt = stop
        for (s, e, _) in local:
            if s > pos:
                nxt = min(nxt, s)
        for (_, s, e) in remote:
            if s > pos:
                nxt = min(nxt, s)
        gaps.append((pos, nxt))
        segments.append((pos, None))
        pos = nxt
    return segments, fetches, gaps


def redistribute(ctx, snap: Optional[HostSnapshot], *,
                 plan: Optional[Callable[[str, Tuple[int, ...]],
                                         Optional[Tuple[int, int]]]] = None,
                 checkpoint: Optional[CheckpointManager] = None,
                 checkpoint_step: Optional[int] = None,
                 template: Any = None,
                 generation: int = -1,
                 ) -> Tuple[Optional[Any], ReshardStats]:
    """Redistribute live state across the cohort (COLLECTIVE — every rank
    calls with the same collective order; ``plan``/``snap`` may differ).

    ``snap`` is this rank's host snapshot (None = holds nothing, e.g. a
    reborn process).  ``plan`` maps leaf path + global shape to this
    rank's target row range (None = replicated whole; ``(x, x)`` = wants
    nothing, the departing-rank case on shrink).  Returns ``(state,
    stats)`` — state is ``unflatten_like(template, ...)`` when a template
    is given, else the flat ``{path: array}`` mapping, or None when the
    cohort holds no state at all and no checkpoint is configured.

    Decision tree per leaf range: local host blocks → peer fetch (spread
    round-robin over holders) → leaf-granular checkpoint read → a
    cohort-wide DMLCError (agreed by allreduce, so no rank trains on a
    half-restored state)."""
    t0 = time.monotonic()
    stats = ReshardStats()
    stats.world = ctx.world_size
    rank = ctx.rank
    has = snap is not None and bool(snap.schema)
    server: Optional[_XferServer] = None
    try:
        with teltrace.span("reshard", generation=generation, rank=rank,
                           world=ctx.world_size, holder=has):
            if has:
                server = _XferServer(snap)
            my_info: Dict[str, Any] = {
                "schema": {p: [list(g), d]
                           for p, (g, d) in snap.schema.items()} if has else {},
                "holds": {p: [[s, e] for (s, e, _) in blocks]
                          for p, blocks in snap.pieces.items()} if has else {},
                "addr": [_my_host(ctx), server.port] if server else None,
            }
            # ownership map: world broadcast rounds (uniform collective
            # order; O(world) tiny messages — cohorts here are hosts, not
            # chips)
            infos = [ctx.broadcast(my_info if r == rank else None, root=r)
                     for r in range(ctx.world_size)]
            schema, holders, addrs = _merge_infos(infos)
            teltrace.add_event("reshard.agreed", leaves=len(schema),
                               holders=len(addrs))

            # my targets
            targets: Dict[str, Tuple[int, int]] = {}
            for path, (gshape, _) in schema.items():
                rows = _rows(gshape)
                tgt = (0, rows) if plan is None else plan(path, gshape)
                if tgt is None:
                    tgt = (0, rows)
                tgt = (max(0, int(tgt[0])), min(rows, int(tgt[1])))
                if tgt[0] < tgt[1]:
                    targets[path] = tgt

            # plan every leaf first, then run ALL peer fetches through one
            # small thread pool: recv_into releases the GIL, so a reborn
            # rank pulls from several survivors concurrently instead of
            # draining leaves one socket at a time
            planned = []          # (path, parts, gaps, fetched_any-box)
            tasks = []            # (planned-index, start, stop, owner, alts)
            for li, path in enumerate(sorted(targets)):
                local = snap.pieces.get(path, []) if has else []
                remote = [h for h in holders.get(path, [])
                          if h[0] != rank and h[0] in addrs]
                segments, fetches, gaps = _plan_leaf(
                    targets[path], local, remote, spread=li + rank)
                parts: Dict[int, np.ndarray] = {
                    s: a for (s, a) in segments if a is not None}
                planned.append([path, parts, gaps, False])
                for (s, e, owner, alts) in fetches:
                    tasks.append((len(planned) - 1, s, e, owner, alts))

            def run_fetch(task):
                idx, s, e, owner, alts = task
                path = planned[idx][0]
                for candidate in [owner] + alts:
                    try:
                        return idx, s, e, _fetch(addrs[candidate], path, s, e)
                    except (OSError, DMLCError) as err:
                        log_warning("reshard: fetch %s[%d:%d) from rank %d "
                                    "failed (%s) — trying next holder",
                                    path, s, e, candidate, err)
                return idx, s, e, None

            if tasks:
                # planned collective schedule (arxiv 2112.01075): group
                # the fetches into holder-balanced rounds whose in-flight
                # bytes stay under DMLC_RESHARD_MAX_BYTES — a reborn rank
                # no longer pulls the whole state at once, and no single
                # survivor serves every fetcher in the same instant.
                # Deterministic planning; execution order cannot change
                # the assembled result (results key on (idx, start)).
                def _row_bytes(path: str) -> int:
                    gshape, dt = schema[path]
                    per = int(np.dtype(dt).itemsize)
                    for d in gshape[1:]:
                        per *= int(d)
                    return per

                budget = env_int("DMLC_RESHARD_MAX_BYTES",
                                 _DEFAULT_BUDGET, minimum=0)
                transfers = [
                    transport_plan.Transfer(
                        planned[idx][0], s, e, owner, alts,
                        nbytes=max(1, e - s) * _row_bytes(planned[idx][0]),
                        tag=task)
                    for task in tasks
                    for (idx, s, e, owner, alts) in (task,)]
                rounds = transport_plan.plan_rounds(
                    transfers, max_bytes=budget if budget > 0 else None,
                    per_holder=env_int("DMLC_RESHARD_PER_HOLDER", 2,
                                       minimum=0))
                metrics.gauge("reshard.rounds").set(float(len(rounds)))
                pool = min(len(tasks),
                           env_int("DMLC_RESHARD_FETCH_THREADS", 8,
                                   minimum=1))
                results = []
                if pool == 1:
                    for rno, rnd in enumerate(rounds):
                        teltrace.add_event(
                            "reshard.round", round=rno, fetches=len(rnd),
                            bytes=sum(t.nbytes for t in rnd))
                        results.extend(run_fetch(t.tag) for t in rnd)
                else:
                    from concurrent.futures import ThreadPoolExecutor
                    with ThreadPoolExecutor(pool) as ex:
                        for rno, rnd in enumerate(rounds):
                            teltrace.add_event(
                                "reshard.round", round=rno,
                                fetches=len(rnd),
                                bytes=sum(t.nbytes for t in rnd))
                            results.extend(
                                ex.map(run_fetch, [t.tag for t in rnd]))
                for idx, s, e, got in results:
                    if got is None:
                        planned[idx][2].append((s, e))
                    else:
                        planned[idx][1][s] = got
                        planned[idx][3] = True
                        stats.bytes_moved += got.nbytes

            assembled: Dict[str, np.ndarray] = {}
            from_ckpt: List[str] = []
            failed: List[str] = []
            for path, parts, gaps, fetched_any in planned:
                gshape, dt = schema[path]
                if gaps and checkpoint is not None:
                    try:
                        _, loaded = checkpoint.restore_leaves(
                            [path], step=checkpoint_step)
                    except DMLCError as err:
                        log_warning("reshard: checkpoint fallback for %s "
                                    "failed (%s)", path, err)
                        loaded = {}
                    if path in loaded:
                        whole = loaded[path]
                        if whole.ndim == 0:
                            whole = whole.reshape((1,))
                        for (s, e) in gaps:
                            parts[s] = whole[s:e]
                        gaps = []
                        from_ckpt.append(path)
                if gaps:
                    failed.append(path)
                    continue
                t0r, t1r = targets[path]
                ordered = [parts[s] for s in sorted(parts)]
                out = (ordered[0] if len(ordered) == 1
                       else np.concatenate(ordered, axis=0))
                if gshape == ():
                    out = out.reshape(())
                expect = ((t1r - t0r,) + tuple(gshape[1:])
                          if gshape else ())
                if tuple(out.shape) != tuple(expect):
                    raise DMLCError(
                        f"reshard: assembled {path!r} has shape "
                        f"{out.shape}, want {expect}")
                out = out.astype(np.dtype(dt), copy=False)
                if out.ndim and not out.flags["C_CONTIGUOUS"]:
                    out = np.ascontiguousarray(out)   # 0-d would gain a dim
                assembled[path] = out
                if path in from_ckpt:
                    stats.leaves_from_checkpoint += 1
                elif fetched_any:
                    stats.leaves_from_peers += 1
                else:
                    stats.leaves_local += 1
            teltrace.add_event(
                "reshard.assembled", from_peers=stats.leaves_from_peers,
                local=stats.leaves_local,
                from_checkpoint=stats.leaves_from_checkpoint,
                bytes_moved=stats.bytes_moved, failed=len(failed))

            # outcome agreement — doubles as the fetch-completion barrier:
            # after it, no peer will dial our server again
            total_failed = int(ctx.allreduce(
                np.array([len(failed)], np.int64), "sum")[0])
            if total_failed:
                metrics.counter("reshard.failures").add(1)
                telflight.dump_incident(
                    "reshard_failed", rank=rank, generation=generation,
                    failed_here=failed[:16], cohort_failed=total_failed)
                raise DMLCError(
                    f"reshard: {total_failed} leaf range(s) unrecoverable "
                    f"cohort-wide (no surviving holder and no checkpoint) "
                    f"— local: {failed[:8]}")
    finally:
        if server is not None:
            server.close()

    stats.wall_s = time.monotonic() - t0
    metrics.gauge("elastic.reshard_wall_s").set(stats.wall_s)
    metrics.counter("reshard.bytes_moved").add(stats.bytes_moved)
    metrics.counter("reshard.leaves_from_peers").add(stats.leaves_from_peers)
    metrics.counter("reshard.leaves_from_checkpoint").add(
        stats.leaves_from_checkpoint)
    if not assembled:
        return None, stats
    log_info("reshard: gen %d restored %d leaves (%d local, %d from peers, "
             "%d from checkpoint, %d bytes moved) in %.3fs", generation,
             len(assembled), stats.leaves_local, stats.leaves_from_peers,
             stats.leaves_from_checkpoint, stats.bytes_moved, stats.wall_s)
    if template is not None:
        return unflatten_like(template, assembled), stats
    return assembled, stats
