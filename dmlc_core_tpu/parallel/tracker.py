"""Rendezvous tracker: rank assignment, allreduce topology, restart recovery —
capability parity with reference ``tracker/dmlc_tracker/tracker.py``.

The reference tracker is a TCP server that (SURVEY §2.5): assigns ranks
(sorted by host for locality, `tracker.py:294-311`), computes a **binary-tree
allreduce topology** plus a **DFS ring** over it for bootstrap/recovery
(`get_tree` :185, `find_share_ring` :193-210, `get_ring` :212-225), brokers
worker⇄worker links, handles ``recover`` for restarted workers (:279-291) and
``print``/``shutdown`` commands, then steps out of the data path.

This implementation keeps the same capability on a fresh JSON-line protocol
(the reference's magic-number binary protocol is an implementation detail of
its C++ client; our client is :mod:`dmlc_core_tpu.parallel.rabit`):

* phase 1 — every worker registers ``(jobid, host, listen_port)``;
* phase 2 — tracker computes tree + ring, sends each worker its rank,
  parent/children and ring prev/next **with addresses**, so link dialing
  needs no further brokering;
* ``recover`` — a restarted worker re-registers with its jobid and receives
  the same rank and fresh neighbor addresses (elastic rejoin,
  reference `tracker.py:279-291`);
* ``print``/``shutdown`` — worker logging relay and teardown (:58-69).

On TPU pods the *data-plane* collectives ride ICI via XLA (see
``parallel.collectives``); this tracker is the control plane: bootstrap for
non-JAX host processes, metadata exchange, elastic restart bookkeeping.  The
``PSTracker`` analog (scheduler bootstrap env) is
:func:`dmlc_core_tpu.parallel.launcher.tpu.jax_coordinator_env`.

**Durability (r17).**  With ``journal=`` (or ``DMLC_TRACKER_JOURNAL``)
the tracker write-ahead-journals rank assignments, worker addresses,
and the link generation through the shared
:class:`~dmlc_core_tpu.utils.durable.StateJournal`.  A SIGKILLed
tracker restarted on the same port + journal re-admits the live cohort:
a worker's ``recover`` from an unchanged address gets its old rank at
the *current* generation — no generation bump, no fleet-wide
re-rendezvous — because its peers' links were never broken (only the
tracker died).
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..transport.frames import send_all
from ..telemetry.aggregate import ResetGuard, merge_states, render_fleet
from ..telemetry.anomaly import StragglerBoard
from ..telemetry.diagnose import DiagnosisEngine
from ..telemetry.exposition import TelemetryServer
from ..telemetry.timeseries import HistoryStore
from ..utils import DMLCError, check, get_env, get_logger, log_info
from ..utils.durable import StateJournal
from ..utils.metrics import metrics

__all__ = ["RabitTracker", "PSTracker", "LivenessBoard", "compute_tree",
           "compute_ring", "recv_json", "send_json", "jittered",
           "replay_tracker_state", "tracker_main", "TRACKER_SNAP_SCHEMA"]

TRACKER_SNAP_SCHEMA = "dmlc.tracker.snapshot/1"


def replay_tracker_state(snapshot: Optional[Dict[str, Any]],
                         records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure replay of tracker journal ``records`` over ``snapshot`` (or
    a blank state); any prefix of a valid log replays without error.

    State shape: ``{"workers": {jobid: {"host", "port", "rank"}},
    "generation": int}``.
    """
    state: Dict[str, Any] = {"workers": {}, "generation": 0}
    if snapshot:
        w = snapshot.get("workers")
        if isinstance(w, dict):
            state["workers"] = json.loads(json.dumps(w))
        state["generation"] = int(snapshot.get("generation", 0))
    for rec in records:
        op = rec.get("op")
        if op == "worker":
            state["workers"][str(rec["jobid"])] = {
                "host": rec.get("host"), "port": rec.get("port"),
                "rank": int(rec.get("rank", -1))}
        elif op == "assign":
            for jobid, rank in (rec.get("ranks") or {}).items():
                w = state["workers"].get(str(jobid))
                if w is not None:
                    w["rank"] = int(rank)
        elif op == "generation":
            state["generation"] = max(state["generation"],
                                      int(rec.get("generation", 0)))
    return state

logger = get_logger()


def jittered(interval_s: float) -> float:
    """``interval_s`` ± ``DMLC_HEARTBEAT_JITTER`` (default 0.2 = ±20%),
    uniformly drawn per call.  Every periodic re-registration loop
    (data-service workers, serving replica agents) sleeps through this:
    a restarted control plane then sees beats *spread over* the interval
    instead of a thundering herd synchronized by the restart itself."""
    frac = float(get_env("DMLC_HEARTBEAT_JITTER", 0.2))
    if frac <= 0.0 or interval_s <= 0.0:
        return interval_s
    frac = min(frac, 0.9)
    spread = random.uniform(-frac, frac)
    return max(0.001, interval_s * (1.0 + spread))


# ---------------- topology math ----------------

def compute_tree(world: int) -> Dict[int, List[int]]:
    """Binary-tree neighbor map {rank: [neighbors]} (reference ``get_tree``
    `tracker.py:185`: parent (r-1)//2, children 2r+1 / 2r+2)."""
    nbrs: Dict[int, List[int]] = {r: [] for r in range(world)}
    for r in range(1, world):
        parent = (r - 1) // 2
        nbrs[parent].append(r)
        nbrs[r].append(parent)
    return nbrs


def tree_parent(rank: int) -> int:
    return (rank - 1) // 2 if rank > 0 else -1


def compute_ring(world: int) -> List[int]:
    """DFS pre-order ring over the binary tree (reference ``find_share_ring``
    `tracker.py:193-210`): consecutive ring hops share a tree edge, so
    recovery traffic rides existing links."""
    order: List[int] = []

    def dfs(r: int) -> None:
        if r >= world:
            return
        order.append(r)
        dfs(2 * r + 1)
        dfs(2 * r + 2)

    dfs(0)
    return order


# ---------------- wire helpers (JSON-line protocol) ----------------

def send_json(sock: socket.socket, obj: dict) -> None:
    data = (json.dumps(obj) + "\n").encode()
    send_all(sock, data)


def recv_json(sock_file) -> Optional[dict]:
    line = sock_file.readline()
    if not line:
        return None
    return json.loads(line)


# ---------------- liveness ----------------

class LivenessBoard:
    """Heartbeat table + death sweep — the liveness half of the tracker,
    factored out so every control-plane server speaking the JSON-line
    protocol (this tracker, the data-service dispatcher in
    :mod:`dmlc_core_tpu.pipeline.data_service.dispatcher`) runs the same
    rules: a member is registered by its first beat, declared dead
    exactly once when silent past the timeout, and revived by any later
    beat.  Metric emission stays at the caller (each server counts its
    own dead under its own literal name).

    Owns its own lock; callers holding a coarser server lock may nest
    board calls inside it (server lock → board lock, one direction only).
    """

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._last: Dict[str, float] = {}
        self._dead: set = set()

    def beat(self, member: str) -> bool:
        """Record a heartbeat (first beat registers the member); True when
        this beat revived a member previously declared dead — the caller
        decides what a misdiagnosed slow-but-alive member means."""
        with self._lock:
            self._last[member] = time.monotonic()
            if member in self._dead:
                self._dead.discard(member)
                return True
            return False

    def forget(self, member: str) -> None:
        """Stop tracking a cleanly-departing member: it stops beating by
        design and must never be declared dead afterwards."""
        with self._lock:
            self._last.pop(member, None)
            self._dead.discard(member)

    def is_dead(self, member: str) -> bool:
        with self._lock:
            return member in self._dead

    def dead_members(self) -> set:
        with self._lock:
            return set(self._dead)

    def sweep(self, eligible=None) -> List[Tuple[str, float]]:
        """Declare members silent past the timeout dead, once each, and
        return them as ``[(member, silence_seconds)]``.  ``eligible``
        optionally filters who may be declared (the tracker excludes
        pre-assignment registrants and completed cohorts)."""
        now = time.monotonic()
        newly: List[Tuple[str, float]] = []
        with self._lock:
            for member, t in self._last.items():
                if member in self._dead or now - t <= self.timeout_s:
                    continue
                if eligible is not None and not eligible(member):
                    continue
                self._dead.add(member)
                newly.append((member, now - t))
        return newly


# ---------------- tracker ----------------

class _WorkerRecord:
    def __init__(self, jobid: str, host: str, port: int):
        self.jobid = jobid
        self.host = host
        self.port = port
        self.rank = -1


class RabitTracker:
    """TCP rendezvous service (reference ``RabitTracker`` `tracker.py:137`).

    >>> t = RabitTracker(num_workers=4); t.start()
    >>> env = t.worker_envs()   # DMLC_TRACKER_URI/PORT for workers
    >>> t.join()                 # until all workers shut down
    """

    #: journal-before-mutate contract (dmlclint ``durable-state``)
    _DURABLE_STATE = ("_workers", "_rank_of", "_generation")
    _DURABLE_FIELDS = ("rank", "host", "port")

    def __init__(self, num_workers: int, host_ip: Optional[str] = None,
                 port: int = 0, max_port: int = 9999,
                 heartbeat_timeout_s: Optional[float] = None,
                 telemetry_port: Optional[int] = None,
                 journal: Optional[str] = None):
        self.num_workers = num_workers
        self.host_ip = host_ip or _default_host_ip()
        # dead-worker detection: workers beat (cmd=heartbeat) and a monitor
        # declares silence beyond the timeout a death — survivors get the
        # same reset_links push a recover registration triggers, so they
        # stop blocking on the corpse NOW instead of when (if) a launcher
        # restarts it.  0 (the default) disables the monitor.
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = get_env("DMLC_HEARTBEAT_TIMEOUT", 0.0)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.liveness = LivenessBoard(self.heartbeat_timeout_s)
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        bound = False
        # port=0 (default) = OS-assigned ephemeral port: concurrent trackers
        # can never collide (the DMLC_TRACKER_PORT env carries the real port
        # to workers).  An explicit port keeps the reference's scan behavior
        # (`tracker.py:141-153`) for fixed-port deployments; a port above
        # max_port (a restart pinned to a prior ephemeral bind) is a
        # single exact candidate, not an empty scan range.
        candidates = [0] if port == 0 else range(port, max(port, max_port) + 1)
        for p in candidates:
            try:
                self._sock.bind((self.host_ip, p))
                self.port = self._sock.getsockname()[1]
                bound = True
                break
            except OSError:
                continue
        if not bound:
            raise DMLCError(f"tracker: no free port in [{port}, {max_port}]")
        self._sock.listen(128)
        self._lock = threading.Condition()
        self._workers: Dict[str, _WorkerRecord] = {}  # jobid → record
        self._rank_of: Dict[str, int] = {}
        self._assigned = False
        self._generation = 0  # bumped on every post-assignment recover
        self._shutdown_count = 0
        self._start_time: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        # fleet telemetry: workers push rank-tagged registry snapshots
        # (cmd=telemetry) and the tracker exposes the merged view on its
        # own /metrics endpoint.  Unset/negative port = disabled.
        if telemetry_port is None:
            p = get_env("DMLC_TRACKER_METRICS_PORT", -1)
            telemetry_port = p if p >= 0 else None
        # durable rendezvous (r17): journal rank assignments + link
        # generation so a restarted tracker re-admits the live cohort
        if journal is None:
            journal = get_env("DMLC_TRACKER_JOURNAL", "") or None
        self._journal: Optional[StateJournal] = None
        self._journal_snap_every = max(16, int(get_env(
            "DMLC_TRACKER_JOURNAL_SNAP_EVERY", 512)))
        if journal:
            self._journal = StateJournal(
                str(journal), snap_schema=TRACKER_SNAP_SCHEMA,
                on_append=metrics.counter("tracker.journal.appends").add,
                on_snapshot=metrics.counter(
                    "tracker.journal.snapshots").add)
            with self._lock:
                self._restore_locked()
        self._telemetry_states: Dict[str, dict] = {}
        # cross-rank straggler detection over the same pushes: every
        # rank-tagged state feeds the board, /metrics carries per-rank
        # straggler_z / straggler_suspect gauges, /stragglers the JSON
        self.straggler_board = StragglerBoard()
        # restarted workers must not drive merged fleet counters
        # backwards: re-base at the ingestion point
        self._reset_guard = ResetGuard()
        # fleet timeline: sample the merged view (rank-tagged pushed
        # histories fold into one queryable /timeline)
        self.history = HistoryStore(
            snapshot_fn=lambda: merge_states(self.telemetry_states()))
        self.telemetry: Optional[TelemetryServer] = None
        if telemetry_port is not None:
            # /diagnose over the MERGED stores: the fleet timeline and
            # the cross-rank straggler board, so one query on the
            # tracker attributes an incident across every rank
            self.telemetry = TelemetryServer(
                port=int(telemetry_port), metrics_fn=self._render_fleet,
                stragglers_fn=self.straggler_board.snapshot,
                timeline_fn=self.history.timeline,
                diagnose_fn=DiagnosisEngine(
                    history=self.history,
                    stragglers_fn=self.straggler_board.snapshot,
                ).endpoint_doc)

    # -- public control --
    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        if self.heartbeat_timeout_s > 0:
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             name="tracker-heartbeat",
                                             daemon=True)
            self._monitor.start()
        if self.telemetry is not None:
            self.telemetry.start()
            self.history.start()
            log_info("tracker fleet metrics at http://%s:%d/metrics",
                     self.host_ip, self.telemetry.port)
        log_info("tracker started at %s:%d for %d workers",
                 self.host_ip, self.port, self.num_workers)

    def worker_envs(self) -> Dict[str, str]:
        """Env contract for workers (reference ``slave_envs`` `tracker.py:182`)."""
        return {
            "DMLC_TRACKER_URI": self.host_ip,
            "DMLC_TRACKER_PORT": str(self.port),
            "DMLC_NUM_WORKER": str(self.num_workers),
        }

    def join(self, timeout: Optional[float] = None) -> None:
        """Block until all workers sent shutdown (reference ``join`` :329-331)."""
        deadline = time.monotonic() + timeout if timeout else None
        with self._lock:
            while self._shutdown_count < self.num_workers:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise DMLCError("tracker join timed out")
                self._lock.wait(remaining)
        if self._start_time is not None:
            log_info("@tracker All of %d nodes got shutdown; %.2f secs between "
                     "start and shutdown", self.num_workers,
                     time.monotonic() - self._start_time)
        self.stop()

    def stop(self) -> None:
        self._stop = True
        self._monitor_stop.set()
        self.history.stop()
        if self.telemetry is not None:
            self.telemetry.stop()
        # shutdown() before close(): close() alone does not wake a
        # thread blocked inside accept(), and the blocked syscall keeps
        # the listen port held — an in-process restart on the same port
        # (the HA drills) would then fail to rebind
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._journal is not None:
            with self._lock:
                self._journal.compact(self._durable_state_locked())
            self._journal.close()

    def _render_fleet(self) -> str:
        with self._lock:
            per_rank = dict(self._telemetry_states)
        page = render_fleet(per_rank, own_snapshot=metrics.snapshot())
        rows = self.straggler_board.series()
        if rows:
            from ..telemetry.exposition import render_series
            page += render_series(rows)
        return page

    def telemetry_states(self) -> Dict[str, dict]:
        """Latest per-rank registry states pushed via ``cmd=telemetry``."""
        with self._lock:
            return dict(self._telemetry_states)

    # -- durable rendezvous (r17) --
    def _jlog(self, op: str, **fields: Any) -> None:
        """One write-ahead record; no-op without a journal.  Callers
        hold ``self._lock`` (the tracker's one big lock — the
        dispatcher's inline-compaction pattern applies)."""
        if self._journal is None:
            return
        self._journal.append({"op": op, "ts": time.time(), **fields})
        if self._journal.appends_since_snapshot >= self._journal_snap_every:
            self._journal.compact(self._durable_state_locked())

    def _durable_state_locked(self) -> Dict[str, Any]:
        return {"workers": {j: {"host": r.host, "port": r.port,
                                "rank": r.rank}
                            for j, r in self._workers.items()},
                "generation": self._generation}

    def _restore_locked(self) -> None:
        snap, records = self._journal.load()
        if snap is None and not records:
            return
        state = replay_tracker_state(snap, records)
        self._workers = {}
        self._rank_of = {}
        for jobid, w in state.get("workers", {}).items():
            rec = _WorkerRecord(jobid, str(w.get("host")),
                                int(w.get("port") or 0))
            rec.rank = int(w.get("rank", -1))
            self._workers[jobid] = rec
            if rec.rank >= 0:
                self._rank_of[jobid] = rec.rank
        self._generation = int(state.get("generation", 0))
        self._assigned = any(r.rank >= 0 for r in self._workers.values())
        for jobid in self._workers:
            # liveness grace: restored workers get a full window to
            # re-attach before the monitor declares them dead
            self.liveness.beat(jobid)
        metrics.counter("tracker.journal.replayed").add(len(records))
        log_info("tracker: replayed %d journal record(s) → %d worker(s)"
                 ", generation %d%s", len(records), len(self._workers),
                 self._generation,
                 " (ranks assigned)" if self._assigned else "")
        self._journal.compact(self._durable_state_locked())

    # -- accept/assign logic --
    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        f = conn.makefile("r")
        try:
            msg = recv_json(f)
            if msg is None:
                return
            cmd = msg.get("cmd")
            if cmd == "print":
                log_info("@worker: %s", msg.get("msg", ""))
            elif cmd == "shutdown":
                with self._lock:
                    self._shutdown_count += 1
                    self.liveness.forget(str(msg.get("jobid", "")))
                    self._lock.notify_all()
            elif cmd == "telemetry":
                # rank-tagged registry state push; last write per rank wins
                # (each push is a full snapshot, not a delta)
                state = msg.get("state")
                if isinstance(state, dict):
                    rank = str(msg.get("rank"))
                    state = self._reset_guard.fold(rank, state)
                    with self._lock:
                        self._telemetry_states[rank] = state
                    # outside the tracker lock: the board has its own
                    self.straggler_board.update(msg.get("rank"), state)
            elif cmd == "heartbeat":
                jobid = str(msg.get("jobid", ""))
                if self.liveness.beat(jobid):
                    # slow-but-alive: the monitor misdiagnosed it; the
                    # next reset/recover round re-links it
                    logger.warning("tracker: worker %r revived by "
                                   "heartbeat", jobid)
            elif cmd in ("start", "recover"):
                self._register_and_reply(conn, msg, recovering=(cmd == "recover"))
            else:
                send_json(conn, {"error": f"unknown cmd {cmd!r}"})
        except (OSError, json.JSONDecodeError, KeyError, ValueError) as e:
            logger.warning("tracker connection error: %s", e)
            try:
                send_json(conn, {"error": f"{type(e).__name__}: {e}"})
            except OSError:
                pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _register_and_reply(self, conn: socket.socket, msg: dict,
                            recovering: bool) -> None:
        jobid = str(msg.get("jobid", ""))
        host = msg.get("host") or conn.getpeername()[0]
        port = int(msg["port"])
        notify: List[Tuple[str, int]] = []
        with self._lock:
            if self._start_time is None:
                self._start_time = time.monotonic()
            self.liveness.beat(jobid)
            rec = self._workers.get(jobid)
            if rec is None:
                rec = _WorkerRecord(jobid, host, port)
                self._jlog("worker", jobid=jobid, host=host, port=port,
                           rank=-1)
                self._workers[jobid] = rec
            else:
                # restarted worker: keep rank, refresh address.  An
                # UNCHANGED address is re-admission after a *tracker*
                # restart (the worker never died, its peers' links are
                # intact) — same rank, current generation, no reset.
                moved = (rec.host, rec.port) != (host, port)
                if moved:
                    self._jlog("worker", jobid=jobid, host=host,
                               port=port, rank=rec.rank)
                rec.host, rec.port = host, port
                if moved and self._assigned and rec.rank >= 0:
                    # MID-JOB restart: surviving peers hold sockets to the
                    # dead incarnation — bump the link generation and push a
                    # reset to every survivor so they drop stale links and
                    # re-rendezvous (reference wait_conn re-linking,
                    # `tracker.py:80-135,279-291`)
                    self._jlog("generation",
                               generation=self._generation + 1)
                    self._generation += 1
                    notify = [(w.host, w.port) for w in self._workers.values()
                              if w.jobid != jobid and w.rank >= 0]
            if not self._assigned:
                # a `recover` can also be the registration that COMPLETES
                # the cohort (a worker that crashed before first rendezvous
                # and was restarted by the launcher retry loop) — assignment
                # must trigger regardless of the command
                if len(self._workers) >= self.num_workers:
                    self._assign_ranks_locked()
                    self._lock.notify_all()
                else:
                    # wait until full cohort present
                    while not self._assigned and not self._stop:
                        self._lock.wait(timeout=1.0)
            rec = self._workers[jobid]
            if rec.rank < 0:
                # a registration beyond the cohort (extra worker, or a server
                # process misusing the worker rendezvous) gets a clean error
                reply = {"error": f"cohort of {self.num_workers} already "
                                  f"assigned; job {jobid!r} is not a member"}
            else:
                reply = self._build_assignment(rec)
            if notify:
                reset = {"cmd": "reset_links",
                         "generation": self._generation,
                         "addresses": {str(w.rank): [w.host, w.port]
                                       for w in self._workers.values()
                                       if w.rank >= 0}}
        for host_port in notify:
            self._notify_reset(host_port, reset)
        send_json(conn, reply)

    def _monitor_loop(self) -> None:
        """Sweep heartbeats; a worker silent past the timeout is declared
        dead ONCE (until it beats or re-registers): bump the link
        generation and push reset_links to the survivors — the same repair
        a recover registration drives, just initiated by the tracker."""
        interval = max(0.1, self.heartbeat_timeout_s / 4.0)
        while not self._monitor_stop.wait(interval):
            notify: List[Tuple[str, int]] = []
            reset: Optional[dict] = None
            with self._lock:
                if not self._assigned:
                    continue
                newly_dead = self.liveness.sweep(
                    eligible=lambda j: (
                        j in self._workers and self._workers[j].rank >= 0
                        and self._shutdown_count < self.num_workers))
                if not newly_dead:
                    continue
                for j, silence in newly_dead:
                    metrics.counter("tracker.dead_workers").add(1)
                    logger.warning(
                        "tracker: worker %r (rank %d) missed heartbeats "
                        "for %.1fs — declaring dead", j,
                        self._workers[j].rank, silence)
                self._jlog("generation", generation=self._generation + 1)
                self._generation += 1
                dead = self.liveness.dead_members()
                notify = [(w.host, w.port) for w in self._workers.values()
                          if w.jobid not in dead and w.rank >= 0]
                reset = {"cmd": "reset_links",
                         "generation": self._generation,
                         "addresses": {str(w.rank): [w.host, w.port]
                                       for w in self._workers.values()
                                       if w.rank >= 0}}
            for host_port in notify:
                self._notify_reset(host_port, reset)

    def _notify_reset(self, addr: Tuple[str, int], reset: dict) -> None:
        """Push a link-reset control message to a survivor's peer listener
        (sentinel rank -2 handshake, then one JSON line).  Retried — a
        dropped notify would strand that survivor waiting for a reset that
        never comes."""
        import struct
        last: Optional[Exception] = None
        for attempt in range(3):
            try:
                with socket.create_connection(addr, timeout=10.0) as s:
                    send_all(s, struct.pack("<q", -2))
                    send_json(s, reset)
                return
            except OSError as e:
                last = e
                time.sleep(0.5 * (attempt + 1))
        logger.warning("tracker: reset notify to %s failed after retries: %s",
                       addr, last)

    def _assign_ranks_locked(self) -> None:
        # sort by host then jobid for locality (reference :294-311)
        ordered = sorted(self._workers.values(),
                         key=lambda r: (r.host, r.jobid))
        self._jlog("assign", ranks={rec.jobid: rank
                                    for rank, rec in enumerate(ordered)})
        for rank, rec in enumerate(ordered):
            rec.rank = rank
            self._rank_of[rec.jobid] = rank
        self._assigned = True
        log_info("@tracker all %d workers registered; ranks assigned",
                 self.num_workers)

    def _addr_of(self, rank: int) -> Tuple[str, int]:
        for rec in self._workers.values():
            if rec.rank == rank:
                return rec.host, rec.port
        raise DMLCError(f"no worker with rank {rank}")

    def _build_assignment(self, rec: _WorkerRecord) -> dict:
        world = self.num_workers
        tree = compute_tree(world)
        ring = compute_ring(world)
        pos = ring.index(rec.rank)
        ring_prev = ring[(pos - 1) % world]
        ring_next = ring[(pos + 1) % world]
        parent = tree_parent(rec.rank)
        children = [c for c in tree[rec.rank] if c != parent]
        return {
            "rank": rec.rank,
            "world": world,
            "parent": parent,
            "children": children,
            "tree_neighbors": tree[rec.rank],
            "ring_prev": ring_prev,
            "ring_next": ring_next,
            "generation": self._generation,
            "addresses": {str(r): list(self._addr_of(r))
                          for r in set(tree[rec.rank] + [ring_prev, ring_next])
                          if r != rec.rank},
        }


class PSTracker:
    """Parameter-server bootstrap — capability parity with reference
    ``PSTracker`` (`tracker.py:336-386`): launch the **scheduler** process
    locally with ``DMLC_ROLE=scheduler`` and hand every worker/server the
    same ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT`` rendezvous env.

    The scheduler binary itself is downstream (ps-lite in the reference;
    here any command — e.g. a process running
    :func:`dmlc_core_tpu.parallel.launcher.tpu.initialize_jax_from_env` as
    coordinator). ``pscmd=None`` skips the spawn and only materializes env,
    matching the reference's behavior when no scheduler command is given.
    """

    def __init__(self, host_ip: Optional[str] = None, port: int = 9100,
                 max_port: int = 9999, pscmd: Optional[List[str]] = None,
                 extra_env: Optional[Dict[str, str]] = None):
        self.host_ip = host_ip or _default_host_ip()
        # reserve a free port and HOLD the socket (a bind-then-close probe
        # races: two trackers scanning concurrently would both pick the
        # same port); released right before the scheduler spawns.
        # port=0 asks the OS for an ephemeral port (no scan, no collisions).
        self.port = None
        self._reserve: Optional[socket.socket] = None
        candidates = [0] if port == 0 else range(port, max_port + 1)
        for p in candidates:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                s.bind((self.host_ip, p))
                self.port = s.getsockname()[1]
                self._reserve = s
                break
            except OSError:
                s.close()
        if self.port is None:
            raise DMLCError(f"pstracker: no free port in [{port}, {max_port}]")
        self.pscmd = pscmd
        self.extra_env = dict(extra_env or {})
        self._proc = None

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_PS_ROOT_URI": self.host_ip,
            "DMLC_PS_ROOT_PORT": str(self.port),
        }

    def start(self) -> None:
        if not self.pscmd:
            return
        import os
        import subprocess
        env = dict(os.environ)
        env.update(self.worker_envs())
        env.update(self.extra_env)
        env["DMLC_ROLE"] = "scheduler"
        if self._reserve is not None:
            # hand the port to the scheduler (it binds it itself, as
            # ps-lite does); SO_REUSEADDR makes the TIME_WAIT-free rebind
            # immediate — the race window is just this spawn
            self._reserve.close()
            self._reserve = None
        self._proc = subprocess.Popen(self.pscmd, env=env)
        log_info("pstracker: scheduler started at %s:%d (pid %d)",
                 self.host_ip, self.port, self._proc.pid)

    def join(self) -> int:
        return self._proc.wait() if self._proc else 0

    def stop(self) -> None:
        if self._reserve is not None:
            self._reserve.close()
            self._reserve = None
        if self._proc and self._proc.poll() is None:
            self._proc.terminate()
            self._proc.wait()


def _default_host_ip() -> str:
    # prefer a routable address; fall back to loopback in sandboxes
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 53))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


def tracker_main(argv=None) -> int:
    """CLI: ``python -m dmlc_core_tpu.parallel.tracker [host=H] [port=N]
    [workers=N] [journal=PREFIX] [heartbeat_timeout=S]`` — serve until
    killed.

    The chaos-drill surface, mirroring ``dispatcher_main``: the HA
    tests run the tracker as a subprocess, SIGKILL it mid-epoch, and
    restart it with the same ``port=`` and ``journal=`` to prove the
    replay re-admits the cohort at the current generation.  The bound
    port is printed as one JSON line on stdout (``{"host": ...,
    "port": ...}``); SIGTERM is a clean stop (journal compacted)."""
    import signal
    import sys
    args = list(sys.argv[1:] if argv is None else argv)
    kw = dict(a.split("=", 1) for a in args)
    t = RabitTracker(
        num_workers=int(kw.get("workers", 1)),
        host_ip=kw.get("host", "127.0.0.1"),
        port=int(kw.get("port", 0)),
        journal=kw.get("journal") or None,
        heartbeat_timeout_s=(float(kw["heartbeat_timeout"])
                             if "heartbeat_timeout" in kw else None))
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda signum, frame: done.set())
    t.start()
    print(json.dumps({"host": t.host_ip, "port": t.port}), flush=True)
    try:
        while not done.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    t.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(tracker_main())
