"""Inside-container bootstrap — capability parity with reference
``tracker/dmlc_tracker/launcher.py`` (the shim that runs *inside* a
YARN/SGE/Mesos container before the worker: hadoop classpath fixup,
``LD_LIBRARY_PATH``, archive unpacking, role derivation, `launcher.py:36-77`).

TPU-native expression: the fixups that matter in a TPU container are the
JAX/libtpu environment rather than the JVM —

* unzip shipped archives into the cwd (same as the reference :60-66);
* derive ``DMLC_TASK_ID``/``DMLC_ROLE`` from scheduler env if the wrapper
  didn't (SGE-style role derivation, reference :68-75);
* map the DMLC contract onto JAX multi-process env
  (``JAX_PROCESS_ID`` ← ``DMLC_TASK_ID`` etc.) so worker code can call
  ``initialize_jax_from_env`` with zero per-cluster logic;
* then ``exec`` the worker command.

Usage (as the command a scheduler runs)::

    python -m dmlc_core_tpu.parallel.launcher.bootstrap -- python train.py
"""

from __future__ import annotations

import os
import sys
import zipfile
from typing import Dict, List, Optional

from ...utils import log_info

__all__ = ["fixup_env", "unpack_archives", "main"]


def unpack_archives(workdir: str = ".") -> List[str]:
    """Unzip any ``*.zip`` shipped into the container cwd (reference
    `launcher.py:60-66` unzips the YARN file cache)."""
    done = []
    for name in sorted(os.listdir(workdir)):
        if name.endswith(".zip"):
            dest = os.path.join(workdir, name[:-4])
            if not os.path.isdir(dest):
                with zipfile.ZipFile(os.path.join(workdir, name)) as z:
                    z.extractall(dest)
                done.append(dest)
    return done


def fixup_env(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Normalize the in-container env: fill DMLC_* from scheduler vars and
    mirror them onto the JAX multi-process contract."""
    e = dict(os.environ if env is None else env)

    # scheduler-specific rank envs → DMLC_TASK_ID (reference SGE derivation;
    # SGE sets the literal 'undefined' for non-array jobs — skip non-digits)
    if "DMLC_TASK_ID" not in e:
        for var, off in (("SLURM_PROCID", 0), ("OMPI_COMM_WORLD_RANK", 0),
                         ("PMI_RANK", 0), ("SGE_TASK_ID", -1)):
            val = e.get(var, "")
            if val.isdigit():
                e["DMLC_TASK_ID"] = str(int(val) + off)
                break

    # role derivation from the server split
    ns = int(e.get("DMLC_NUM_SERVER", "0") or 0)
    if "DMLC_ROLE" not in e and "DMLC_TASK_ID" in e:
        e["DMLC_ROLE"] = ("server" if int(e["DMLC_TASK_ID"]) < ns
                          else "worker")

    # DMLC contract → JAX multi-process contract. Only WORKERS join the
    # JAX process group (servers are host-side PS processes), and the task
    # id space is global (servers 0..ns-1, workers ns..), so the jax
    # process id is task_id - num_server
    if ("JAX_PROCESS_ID" not in e and "DMLC_TASK_ID" in e
            and e.get("DMLC_ROLE", "worker") == "worker"):
        e["JAX_PROCESS_ID"] = str(int(e["DMLC_TASK_ID"]) - ns)
    if "JAX_NUM_PROCESSES" not in e and "DMLC_NUM_WORKER" in e:
        e["JAX_NUM_PROCESSES"] = e["DMLC_NUM_WORKER"]
    return e


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] == "--":
        args = args[1:]
    if not args:
        print("usage: python -m dmlc_core_tpu.parallel.launcher.bootstrap "
              "-- <worker command...>", file=sys.stderr)
        return 2
    unpacked = unpack_archives()
    if unpacked:
        log_info("bootstrap: unpacked %s", unpacked)
    env = fixup_env()
    os.execvpe(args[0], args, env)  # never returns


if __name__ == "__main__":
    sys.exit(main())
