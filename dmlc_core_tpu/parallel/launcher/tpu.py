"""`--cluster tpu`: map ranks onto a JAX multi-process (multi-host TPU) job.

The reference's PS tracker boots a scheduler and hands every process
rendezvous env (`tracker.py:336-386`).  On TPU pods that role collapses into
the **JAX coordination service** (SURVEY §5.8): process 0 is the coordinator;
every process calls ``jax.distributed.initialize(coordinator, n, id)`` and
the ICI/DCN mesh replaces brokered sockets.

This launcher spawns one process per TPU host (or per requested worker when
simulating locally), exporting both contracts:

* ``DMLC_*``  — rank/world/tracker env (our rabit tracker, control plane)
* ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` —
  consumed by :func:`initialize_jax_from_env` in worker code.

On a real pod slice, process placement is normally handled by the platform
(GKE/queued resources); this backend then only materializes env and execs the
worker once per host.
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading
from typing import Dict

from ...utils import get_env, log_info

__all__ = ["submit", "jax_coordinator_env", "initialize_jax_from_env"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def jax_coordinator_env(num_processes: int, host_ip: str = "127.0.0.1",
                        port: int = 0) -> Dict[str, str]:
    port = port or _free_port()
    return {
        "JAX_COORDINATOR_ADDRESS": f"{host_ip}:{port}",
        "JAX_NUM_PROCESSES": str(num_processes),
    }


def initialize_jax_from_env() -> None:
    """Worker-side bootstrap: call before first jax use.  Reads the env this
    launcher (or the platform) exported and joins the JAX coordination
    service — the TPU analog of the rabit client connecting to the tracker."""
    import jax
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return  # single-process
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # CPU-backend cross-process collectives need the gloo transport;
        # jaxes that pick it automatically no longer expose the knob
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — option absent: automatic
            pass
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=get_env("JAX_NUM_PROCESSES", 1),
        process_id=get_env("JAX_PROCESS_ID",
                           get_env("DMLC_TASK_ID", 0)),
    )


def _free_port_run(length: int, tries: int = 50) -> int:
    """A base port with ``length`` CONSECUTIVE free ports above it:
    elastic generation g binds base+g, so probing only the base would
    leave post-crash generations to collide with whatever else is bound
    in the ephemeral range (the rejoin would wedge at initialize)."""
    for _ in range(tries):
        socks = []
        try:
            s0 = socket.socket()
            s0.bind(("", 0))
            base = s0.getsockname()[1]
            socks.append(s0)
            for i in range(1, length + 1):
                s = socket.socket()
                s.bind(("", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise OSError(f"no run of {length + 1} consecutive free ports found")


def submit(args, tracker_envs: Dict[str, str]) -> int:
    n = args.num_workers
    coord = jax_coordinator_env(n, host_ip=args.host_ip or "127.0.0.1")
    elastic = bool(getattr(args, "elastic", False))
    # elastic retry is OPT-IN: plain jax.distributed worker code cannot
    # admit a reborn process (the coordination service has no elasticity),
    # so respawning a crashed rank in a non-elastic job would trade a
    # fast failure for attempts x init-timeout of hang.  With --elastic,
    # worker code is expected to drive ElasticJaxMesh, whose generation g
    # binds DMLC_ELASTIC_BASE_PORT + g — reserve a consecutive port run so
    # post-crash generations don't collide with other services.
    max_attempts = max(1, getattr(args, "max_attempts", 1)) if elastic else 1
    elastic_base = str(_free_port_run(8)) if elastic else ""
    results = [0] * n
    threads = []
    for i in range(n):
        env = dict(os.environ)
        env.update(tracker_envs)
        env.update(coord)
        env.update(args.extra_env)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_TASK_ID": str(i),
            "JAX_PROCESS_ID": str(i),
            "DMLC_NUM_WORKER": str(n),
            "DMLC_JOB_CLUSTER": "tpu",
        })
        if elastic:
            env["DMLC_ELASTIC_BASE_PORT"] = elastic_base

        def run(env=env, slot=i):
            # per-slot retry with a bumped attempt counter — the launcher
            # half of elastic rejoin: the reborn process registers rabit
            # `recover` and (when using ElasticJaxMesh) drags the cohort
            # to a fresh jax.distributed generation at its sync point.
            # Mirrors the local launcher's retry contract.
            attempt = 0
            while True:
                env_try = dict(env, DMLC_NUM_ATTEMPT=str(attempt))
                rc = subprocess.call(args.command, env=env_try)
                if rc == 0:
                    results[slot] = 0
                    return
                attempt += 1
                log_info("tpu worker %d exited rc=%d (attempt %d/%d)",
                         slot, rc, attempt, max_attempts)
                if attempt >= max_attempts:
                    results[slot] = rc
                    return

        t = threading.Thread(target=run, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    rc = next((r for r in results if r), 0)
    log_info("tpu job finished rc=%d", rc)
    return rc
