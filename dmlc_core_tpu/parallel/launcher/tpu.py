"""`--cluster tpu`: map ranks onto a JAX multi-process (multi-host TPU) job.

The reference's PS tracker boots a scheduler and hands every process
rendezvous env (`tracker.py:336-386`).  On TPU pods that role collapses into
the **JAX coordination service** (SURVEY §5.8): process 0 is the coordinator;
every process calls ``jax.distributed.initialize(coordinator, n, id)`` and
the ICI/DCN mesh replaces brokered sockets.

This launcher spawns one process per TPU host (or per requested worker when
simulating locally), exporting both contracts:

* ``DMLC_*``  — rank/world/tracker env (our rabit tracker, control plane)
* ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` —
  consumed by :func:`initialize_jax_from_env` in worker code.

On a real pod slice, process placement is normally handled by the platform
(GKE/queued resources); this backend then only materializes env and execs the
worker once per host.
"""

from __future__ import annotations

import os
import socket
import subprocess
import threading
from typing import Dict

from ...utils import get_env, log_info

__all__ = ["submit", "jax_coordinator_env", "initialize_jax_from_env"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def jax_coordinator_env(num_processes: int, host_ip: str = "127.0.0.1",
                        port: int = 0) -> Dict[str, str]:
    port = port or _free_port()
    return {
        "JAX_COORDINATOR_ADDRESS": f"{host_ip}:{port}",
        "JAX_NUM_PROCESSES": str(num_processes),
    }


def initialize_jax_from_env() -> None:
    """Worker-side bootstrap: call before first jax use.  Reads the env this
    launcher (or the platform) exported and joins the JAX coordination
    service — the TPU analog of the rabit client connecting to the tracker."""
    import jax
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return  # single-process
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=get_env("JAX_NUM_PROCESSES", 1),
        process_id=get_env("JAX_PROCESS_ID",
                           get_env("DMLC_TASK_ID", 0)),
    )


def submit(args, tracker_envs: Dict[str, str]) -> int:
    n = args.num_workers
    coord = jax_coordinator_env(n, host_ip=args.host_ip or "127.0.0.1")
    results = [0] * n
    threads = []
    for i in range(n):
        env = dict(os.environ)
        env.update(tracker_envs)
        env.update(coord)
        env.update(args.extra_env)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_TASK_ID": str(i),
            "JAX_PROCESS_ID": str(i),
            "DMLC_NUM_WORKER": str(n),
            "DMLC_JOB_CLUSTER": "tpu",
        })

        def run(env=env, slot=i):
            results[slot] = subprocess.call(args.command, env=env)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    rc = next((r for r in results if r), 0)
    log_info("tpu job finished rc=%d", rc)
    return rc
