"""File/archive shipping for job submission — capability parity with the
reference's file cache (``tracker/dmlc_tracker/opts.py:6-36``
``get_cache_file_set`` + the YARN file-cache wiring ``yarn.py:35-42`` and
auto-cached executable).

Three pieces:

* :func:`resolve` — scan the command line for local files (auto-cache),
  merge ``--files``/``--archives``, and rewrite the command to use staged
  names (``../../kmeans ../kmeans.conf`` → ``./kmeans kmeans.conf``).
* :func:`stage_into` — python-side staging for same-host backends (local):
  copy files (exec bit preserved) and extract archives into the worker cwd.
* :func:`stage_snippet` — shell staging for script/inline backends
  (slurm/sge/mpi/yarn/mesos): each task makes a private scratch dir, copies
  the cached files from their absolute source paths (reachable via the
  cluster's shared filesystem, as the reference assumes outside YARN) and
  cds into it.  The ssh backend rsyncs instead (no shared-FS assumption).
"""

from __future__ import annotations

import os
import shlex
import shutil
import tarfile
import zipfile
from typing import List, Tuple

__all__ = ["resolve", "stage_into", "stage_snippet", "extract_archive"]


def resolve(command: List[str], files: List[str], archives: List[str],
            auto_file_cache: bool = True
            ) -> Tuple[List[str], List[str], List[str]]:
    """Return ``(cache_files, cache_archives, rewritten_command)``.

    With ``auto_file_cache`` every command token naming an existing local
    file is cached and rewritten to ``./<basename>`` (the executable ships
    with the job instead of being found by luck on the worker).
    """
    seen = set()

    def _add(lst: List[str], f: str) -> None:
        a = os.path.abspath(f)
        if a not in seen:
            seen.add(a)
            lst.append(a)

    cache: List[str] = []
    cmds: List[str] = []
    if auto_file_cache:
        cwd = os.getcwd()
        for tok in command:
            # only auto-ship files under the submit cwd: system paths like
            # the interpreter (/usr/bin/python) must run in place — copying
            # a venv python elsewhere breaks its prefix resolution (the
            # reference caches ANY existing path, opts.py:27; this is the
            # safe subset of that behavior)
            a = os.path.abspath(tok)
            if os.path.isfile(tok) and a.startswith(cwd.rstrip(os.sep)
                                                    + os.sep):
                _add(cache, tok)
                cmds.append("./" + os.path.basename(tok))
            else:
                cmds.append(tok)
    else:
        cmds = list(command)
    for f in files:
        if os.path.exists(f):
            _add(cache, f)
    arch: List[str] = []
    for f in archives:
        if os.path.exists(f):
            _add(arch, f)
    return cache, arch, cmds


def unpack_command(path: str, dest: str = ".") -> str:
    """The shell command extracting archive ``path`` into ``dest`` — the
    ONE home for the zip/tar dispatch used by every shell-staging backend."""
    q = shlex.quote(path)
    qd = dest if dest.startswith('"') else shlex.quote(dest)
    if path.endswith(".zip"):
        return f"unzip -oq {q} -d {qd}"
    return f"tar -xf {q} -C {qd}"


def extract_archive(path: str, dest: str) -> None:
    """Extract a zip/tar archive into ``dest`` (the YARN file-cache unzip
    behavior for ``--archives``; ships e.g. python libraries)."""
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as z:
            z.extractall(dest)
    elif tarfile.is_tarfile(path):
        with tarfile.open(path) as t:
            t.extractall(dest)
    else:
        # not an archive: behave like a plain cached file
        shutil.copy2(path, os.path.join(dest, os.path.basename(path)))


def stage_into(dest: str, cache_files: List[str],
               cache_archives: List[str]) -> None:
    """Copy cached files (+x preserved via copy2) and extract archives into
    ``dest`` — the python-side analog of the YARN local resource download."""
    os.makedirs(dest, exist_ok=True)
    for f in cache_files:
        shutil.copy2(f, os.path.join(dest, os.path.basename(f)))
    for a in cache_archives:
        extract_archive(a, dest)


def stage_snippet(cache_files: List[str], cache_archives: List[str],
                  mode: str = "copy") -> str:
    """Shell lines staging the cache for script/inline backends.

    ``mode='copy'`` (slurm/sge/mpi/mesos): make a task-private dir, copy
    the cached files from their absolute submit-host paths (reachable over
    the cluster's shared filesystem), extract archives, cd there.

    ``mode='cwd'`` (yarn): the scheduler's own file cache already placed
    the files in the container cwd (DistributedShell ``-shell_files``), so
    only archive extraction of ``./<basename>`` remains.
    """
    if not cache_files and not cache_archives:
        return ""
    lines: List[str] = []
    # any staging step failing must kill the attempt loudly, not leave the
    # task running the wrong (empty) cwd until retries exhaust
    guard = ' || { echo "dmlc: file-cache staging failed" >&2; exit 97; }'
    if mode == "copy":
        lines.append(
            'DMLC_STAGE_DIR="$(mktemp -d "${TMPDIR:-/tmp}/dmlc_stage_XXXXXX")"')
        for f in cache_files:
            lines.append(f'cp -f {shlex.quote(f)} "$DMLC_STAGE_DIR/"' + guard)
    for a in cache_archives:
        if mode == "copy":
            lines.append(unpack_command(a, '"$DMLC_STAGE_DIR"') + guard)
        else:
            lines.append(unpack_command("./" + os.path.basename(a)) + guard)
    if mode == "copy":
        lines.append('cd "$DMLC_STAGE_DIR"')
    return "\n".join(lines)
