"""YARN launcher — capability parity with reference ``tracker/dmlc_tracker/
yarn.py`` (+ the Java client/AM under ``tracker/yarn/``).

The reference builds a custom Java ApplicationMaster (`yarn.py:35-42`,
`Client.java`, `ApplicationMaster.java`) that negotiates containers, injects
the ``DMLC_*`` env and restarts failed tasks up to ``DMLC_MAX_ATTEMPT``
with node blacklisting (`ApplicationMaster.java:73-74,535-563`).

TPU-native expression: no custom AM — we target YARN's stock
**DistributedShell** application with a generated wrapper script that maps
the container index onto ``DMLC_TASK_ID``/``DMLC_ROLE`` and exports the
tracker rendezvous env. Failure handling is two-tier:

* **task crash** → the AM's maxNumAttempt policy maps onto
  ``--max-attempts`` (forwarded as ``DMLC_MAX_ATTEMPT``) driving an
  **in-place retry loop** inside the container — the worker restarts with
  a stable task id and an incremented ``DMLC_NUM_ATTEMPT``, which flips
  the rabit client into the tracker's ``recover`` protocol
  (`tracker.py:279-291` analog).
* **node/container death** (the case the reference's Java AM handles by
  re-requesting containers with node blacklisting,
  `ApplicationMaster.java:73-74,535-563`) → stock DistributedShell cannot
  re-request containers inside a running app, so the launcher reacquires
  at the *application* granularity: when the app finishes FAILED, it
  queries the RM REST API for diagnostics
  (``/ws/v1/cluster/apps/{id}``, endpoint from ``DMLC_YARN_RM_HTTP``),
  logs them, and **resubmits the whole app** — every container is
  allocated fresh, and YARN's own unhealthy-node tracking keeps the dead
  node out of the new allocation.  Bounded by ``DMLC_YARN_APP_ATTEMPTS``
  (default: ``--max-attempts``).  The tracker keeps listening across
  resubmits, so the fresh cohort re-rendezvouses at a new generation.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
from typing import Dict, List

from ...utils import DMLCError, log_info
from ...utils.parameter import env_int, get_env
from .wrapper import write_wrapper_script

__all__ = ["submit_yarn", "build_yarn_command", "rm_app_report"]

# CONTAINER_ID ends in _<attempt>_<id>; ids start at 1 and container 1 is
# the AM itself, so first-allocation task index = id - 2 (the shared
# wrapper fails fast on non-numeric/out-of-range ids)
_RANK_SNIPPET = '''cid="${CONTAINER_ID##*_}"
cid="$((10#$cid))"
export DMLC_TASK_ID="$((cid - 2))"'''


def build_yarn_command(args, tracker_envs: Dict[str, str]) -> List[str]:
    """Generate the DistributedShell submission (one container per task)."""
    # stage_mode='cwd': DistributedShell's own file cache (-shell_files)
    # delivers cached files into the container cwd, so the wrapper only
    # extracts archives (reference ships through the YARN file cache the
    # same way, yarn.py:35-42)
    script = write_wrapper_script(args, tracker_envs, "yarn", _RANK_SNIPPET,
                                  stage_mode="cwd")
    nproc = args.num_workers + args.num_servers
    hadoop = os.environ.get("HADOOP_HOME", "")
    hadoop_bin = os.path.join(hadoop, "bin", "hadoop") if hadoop else "hadoop"
    jar = get_env(
        "DMLC_YARN_DSHELL_JAR",
        "hadoop-yarn-applications-distributedshell.jar")
    cmd = [
        hadoop_bin, "org.apache.hadoop.yarn.applications."
                    "distributedshell.Client",
        "-jar", jar,
        "-shell_script", script,
        "-num_containers", str(nproc),
        "-container_memory", str(args.worker_memory_mb),
        "-container_vcores", str(args.worker_cores),
    ]
    cache = ((getattr(args, "cache_files", None) or [])
             + (getattr(args, "cache_archives", None) or []))
    if cache:
        cmd += ["-shell_files", ",".join(cache)]
    if args.jobname:
        cmd += ["-appname", args.jobname]
    if args.yarn_queue:
        cmd += ["-queue", args.yarn_queue]
    if getattr(args, "yarn_app_classpath", None):
        # reference opts.py:118: forwarded into the container env
        cmd += ["-shell_env",
                f"DMLC_YARN_APP_CLASSPATH={args.yarn_app_classpath}"]
    return cmd


_APP_ID_RE = re.compile(r"application_\d+_\d+")


def rm_app_report(app_id: str, rm_http: str = "",
                  timeout: float = 10.0) -> Dict:
    """Best-effort ResourceManager REST query for one application
    (``GET {rm}/ws/v1/cluster/apps/{app_id}``) → the ``app`` object
    (``state``, ``finalStatus``, ``diagnostics``, …), or ``{}`` when the
    endpoint is unset/unreachable — diagnostics must never turn a launch
    failure into a launcher crash."""
    import urllib.request
    rm = rm_http or get_env("DMLC_YARN_RM_HTTP", "")
    if not rm or not app_id:
        return {}
    url = f"{rm.rstrip('/')}/ws/v1/cluster/apps/{app_id}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode()).get("app", {}) or {}
    except Exception as e:  # noqa: BLE001 — best-effort telemetry
        log_info("yarn: RM REST report unavailable (%s: %s)",
                 type(e).__name__, e)
        return {}


def submit_yarn(args, tracker_envs: Dict[str, str]) -> int:
    # container-granularity mode (VERDICT r4 #8): one single-container app
    # per task over the RM REST API, supervised with the reference AM's
    # retry/blacklist/abort policy — a container death restarts only that
    # task's app.  Opt in with DMLC_YARN_MODE=rest (+ DMLC_YARN_RM_HTTP);
    # the stock-DistributedShell path below stays the zero-config default.
    if get_env("DMLC_YARN_MODE", "dshell") == "rest":
        from .yarn_am import supervise_from_args
        if args.dry_run:
            nproc = args.num_workers + args.num_servers
            log_info("yarn (dry run, rest mode): would submit %d single-"
                     "container apps to %s (max_attempts=%d)", nproc,
                     get_env("DMLC_YARN_RM_HTTP", "<unset>"),
                     max(1, getattr(args, "max_attempts", 1)))
            return 0
        return supervise_from_args(args, tracker_envs)
    cmd = build_yarn_command(args, tracker_envs)
    script = cmd[cmd.index("-shell_script") + 1]
    log_info("yarn%s: %s", " (dry run)" if args.dry_run else "",
             " ".join(cmd))
    app_attempts = env_int("DMLC_YARN_APP_ATTEMPTS",
                           max(1, getattr(args, "max_attempts", 1)),
                           minimum=1)
    try:
        if args.dry_run:
            with open(script) as f:
                log_info("yarn wrapper script:\n%s", f.read())
            return 0
        rc = 1
        for attempt in range(1, app_attempts + 1):
            # line-streaming tee: a training app runs for hours and the
            # client prints continuous AM progress — the operator must see
            # it live, and only the application id needs capturing
            app_id = ""
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)
            assert proc.stdout is not None
            for line in proc.stdout:
                print(line, end="", flush=True)
                if not app_id:
                    m = _APP_ID_RE.search(line)
                    if m:
                        app_id = m.group(0)
            rc = proc.wait()
            if rc == 0:
                return 0
            report = rm_app_report(app_id)
            if report:
                log_info("yarn: %s finished %s/%s: %s", app_id,
                         report.get("state"), report.get("finalStatus"),
                         (report.get("diagnostics") or "").strip()[:500])
            if attempt < app_attempts:
                # application-level reacquire: a fresh submission allocates
                # every container anew (the app-granularity analog of the
                # reference AM's container re-request; YARN itself keeps
                # unhealthy nodes out of the new allocation)
                log_info("yarn: app failed (rc %d) — resubmitting with "
                         "fresh containers (attempt %d/%d)",
                         rc, attempt + 1, app_attempts)
        return rc
    except FileNotFoundError as e:
        raise DMLCError(
            f"yarn submit needs the hadoop CLI on PATH (or HADOOP_HOME): {e}"
        ) from e
    finally:
        try:
            os.unlink(script)
        except OSError:
            pass
