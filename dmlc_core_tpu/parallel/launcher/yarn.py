"""YARN launcher — capability parity with reference ``tracker/dmlc_tracker/
yarn.py`` (+ the Java client/AM under ``tracker/yarn/``).

The reference builds a custom Java ApplicationMaster (`yarn.py:35-42`,
`Client.java`, `ApplicationMaster.java`) that negotiates containers, injects
the ``DMLC_*`` env and restarts failed tasks up to ``DMLC_MAX_ATTEMPT``
with node blacklisting (`ApplicationMaster.java:73-74,535-563`).

TPU-native expression: no custom AM — we target YARN's stock
**DistributedShell** application with a generated wrapper script that maps
the container index onto ``DMLC_TASK_ID``/``DMLC_ROLE`` and exports the
tracker rendezvous env. Failure handling: the AM's maxNumAttempt policy
maps onto ``--max-attempts`` (forwarded as ``DMLC_MAX_ATTEMPT``) driving an
**in-place retry loop** inside the container — the worker restarts with a
stable task id and an incremented ``DMLC_NUM_ATTEMPT``, which flips the
rabit client into the tracker's ``recover`` protocol (`tracker.py:279-291`
analog). Container-*level* replacement (a fresh container with a new id) is
not supported by stock DistributedShell; a deployment that needs it should
front this launcher with a custom AM, as the reference does.
"""

from __future__ import annotations

import os
import subprocess
from typing import Dict, List

from ...utils import DMLCError, log_info
from .wrapper import write_wrapper_script

__all__ = ["submit_yarn", "build_yarn_command"]

# CONTAINER_ID ends in _<attempt>_<id>; ids start at 1 and container 1 is
# the AM itself, so first-allocation task index = id - 2 (the shared
# wrapper fails fast on non-numeric/out-of-range ids)
_RANK_SNIPPET = '''cid="${CONTAINER_ID##*_}"
cid="$((10#$cid))"
export DMLC_TASK_ID="$((cid - 2))"'''


def build_yarn_command(args, tracker_envs: Dict[str, str]) -> List[str]:
    """Generate the DistributedShell submission (one container per task)."""
    # stage_mode='cwd': DistributedShell's own file cache (-shell_files)
    # delivers cached files into the container cwd, so the wrapper only
    # extracts archives (reference ships through the YARN file cache the
    # same way, yarn.py:35-42)
    script = write_wrapper_script(args, tracker_envs, "yarn", _RANK_SNIPPET,
                                  stage_mode="cwd")
    nproc = args.num_workers + args.num_servers
    hadoop = os.environ.get("HADOOP_HOME", "")
    hadoop_bin = os.path.join(hadoop, "bin", "hadoop") if hadoop else "hadoop"
    jar = os.environ.get(
        "DMLC_YARN_DSHELL_JAR",
        "hadoop-yarn-applications-distributedshell.jar")
    cmd = [
        hadoop_bin, "org.apache.hadoop.yarn.applications."
                    "distributedshell.Client",
        "-jar", jar,
        "-shell_script", script,
        "-num_containers", str(nproc),
        "-container_memory", str(args.worker_memory_mb),
        "-container_vcores", str(args.worker_cores),
    ]
    cache = ((getattr(args, "cache_files", None) or [])
             + (getattr(args, "cache_archives", None) or []))
    if cache:
        cmd += ["-shell_files", ",".join(cache)]
    if args.jobname:
        cmd += ["-appname", args.jobname]
    if args.yarn_queue:
        cmd += ["-queue", args.yarn_queue]
    if getattr(args, "yarn_app_classpath", None):
        # reference opts.py:118: forwarded into the container env
        cmd += ["-shell_env",
                f"DMLC_YARN_APP_CLASSPATH={args.yarn_app_classpath}"]
    return cmd


def submit_yarn(args, tracker_envs: Dict[str, str]) -> int:
    cmd = build_yarn_command(args, tracker_envs)
    script = cmd[cmd.index("-shell_script") + 1]
    log_info("yarn%s: %s", " (dry run)" if args.dry_run else "",
             " ".join(cmd))
    try:
        if args.dry_run:
            with open(script) as f:
                log_info("yarn wrapper script:\n%s", f.read())
            return 0
        return subprocess.call(cmd)
    except FileNotFoundError as e:
        raise DMLCError(
            f"yarn submit needs the hadoop CLI on PATH (or HADOOP_HOME): {e}"
        ) from e
    finally:
        try:
            os.unlink(script)
        except OSError:
            pass
