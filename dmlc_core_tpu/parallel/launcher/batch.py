"""Batch-scheduler launchers: Slurm, SGE, and MPI — capability parity with
reference ``slurm.py`` (`slurm.py:38-60`), ``sge.py`` (`sge.py:30-43`) and
``mpi.py`` (`mpi.py:12-36`).

Each backend materializes the DMLC_* env contract and delegates process
placement to the scheduler.  Worker rank comes from the scheduler's own task
id env (SLURM_PROCID / SGE_TASK_ID / OMPI_COMM_WORLD_RANK / PMI_RANK), which
the generated wrapper maps onto DMLC_TASK_ID."""

from __future__ import annotations

import os
import subprocess
from typing import Dict

from ...utils import log_info
from .wrapper import write_wrapper_script

__all__ = ["submit_slurm", "submit_sge", "submit_mpi"]


def _launch(args, cmd, label: str, script: str) -> int:
    log_info("%s%s: %s", label, " (dry run)" if args.dry_run else "",
             " ".join(cmd))
    try:
        if args.dry_run:
            # the wrapper IS the substance of the submission: show it,
            # since the temp file is removed below
            with open(script) as f:
                log_info("%s wrapper script:\n%s", label, f.read())
            return 0
        # srun / qsub -sync y / mpirun all block until the job ends, so the
        # wrapper can be removed once the call returns
        return subprocess.call(cmd)
    finally:
        try:
            os.unlink(script)
        except OSError:
            pass


def submit_slurm(args, tracker_envs: Dict[str, str]) -> int:
    nproc = args.num_workers + args.num_servers
    script = write_wrapper_script(
        args, tracker_envs, "slurm",
        'export DMLC_TASK_ID="${SLURM_PROCID}"')
    cmd = ["srun", "-n", str(nproc)]
    if args.slurm_partition:
        cmd += ["-p", args.slurm_partition]
    # reference opts.py --slurm-worker-nodes/--slurm-server-nodes: pin the
    # node count; one srun hosts both roles here, so the counts add
    nodes = ((args.slurm_worker_nodes or 0)
             + (args.slurm_server_nodes or 0))
    if nodes:
        cmd += ["-N", str(nodes)]
    cmd.append(script)
    return _launch(args, cmd, "slurm", script)


def submit_sge(args, tracker_envs: Dict[str, str]) -> int:
    nproc = args.num_workers + args.num_servers
    # SGE_TASK_ID is 1-based
    script = write_wrapper_script(
        args, tracker_envs, "sge",
        'export DMLC_TASK_ID="$((SGE_TASK_ID - 1))"')
    cmd = ["qsub", "-cwd", "-t", f"1-{nproc}", "-b", "y", "-sync", "y"]
    if args.sge_queue:
        cmd += ["-q", args.sge_queue]
    if getattr(args, "sge_log_dir", None):
        # reference opts.py:108 --sge-log-dir: qsub stdout/stderr land here
        cmd += ["-o", args.sge_log_dir, "-e", args.sge_log_dir]
    cmd.append(script)
    return _launch(args, cmd, "sge", script)


def submit_mpi(args, tracker_envs: Dict[str, str]) -> int:
    nproc = args.num_workers + args.num_servers
    # OpenMPI vs MPICH rank env detected in the wrapper at runtime
    script = write_wrapper_script(
        args, tracker_envs, "mpi",
        'export DMLC_TASK_ID="${OMPI_COMM_WORLD_RANK:-${PMI_RANK:-0}}"')
    cmd = ["mpirun", "-n", str(nproc)]
    if args.host_file:
        cmd += ["--hostfile", args.host_file]
    cmd.append(script)
    return _launch(args, cmd, "mpi", script)
