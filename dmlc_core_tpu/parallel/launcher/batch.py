"""Batch-scheduler launchers: Slurm, SGE, and MPI — capability parity with
reference ``slurm.py`` (`slurm.py:38-60`), ``sge.py`` (`sge.py:30-43`) and
``mpi.py`` (`mpi.py:12-36`).

Each backend materializes the DMLC_* env contract and delegates process
placement to the scheduler.  Worker rank comes from the scheduler's own task
id env (SLURM_PROCID / SGE_TASK_ID / OMPI_COMM_WORLD_RANK / PMI_RANK), which
the generated wrapper maps onto DMLC_TASK_ID."""

from __future__ import annotations

import os
import shlex
import stat
import subprocess
import tempfile
from typing import Dict, List

from ...utils import DMLCError, log_info

__all__ = ["submit_slurm", "submit_sge", "submit_mpi"]


def _wrapper_script(args, tracker_envs: Dict[str, str], rank_env: str,
                    cluster: str) -> str:
    env = dict(tracker_envs)
    env.update(args.extra_env)
    env.update({
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_JOB_CLUSTER": cluster,
    })
    exports = "\n".join(f"export {k}={shlex.quote(v)}" for k, v in env.items())
    ns = args.num_servers
    cmd = " ".join(shlex.quote(c) for c in args.command)
    body = f"""#!/bin/bash
{exports}
export DMLC_TASK_ID="${{{rank_env}}}"
if [ "${{DMLC_TASK_ID}}" -lt "{ns}" ]; then
  export DMLC_ROLE=server
else
  export DMLC_ROLE=worker
fi
exec {cmd}
"""
    fd, path = tempfile.mkstemp(prefix="dmlc_run_", suffix=".sh")
    with os.fdopen(fd, "w") as f:
        f.write(body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR)
    return path


def submit_slurm(args, tracker_envs: Dict[str, str]) -> int:
    nproc = args.num_workers + args.num_servers
    script = _wrapper_script(args, tracker_envs, "SLURM_PROCID", "slurm")
    cmd = ["srun", "-n", str(nproc)]
    if args.slurm_partition:
        cmd += ["-p", args.slurm_partition]
    cmd.append(script)
    log_info("slurm: %s", " ".join(cmd))
    return subprocess.call(cmd)


def submit_sge(args, tracker_envs: Dict[str, str]) -> int:
    nproc = args.num_workers + args.num_servers
    # SGE_TASK_ID is 1-based; shift inside the wrapper
    script = _wrapper_script(args, tracker_envs, "DMLC_SGE_RANK", "sge")
    with open(script) as f:
        body = f.read().replace(
            'export DMLC_TASK_ID="${DMLC_SGE_RANK}"',
            'export DMLC_TASK_ID="$((SGE_TASK_ID - 1))"')
    with open(script, "w") as f:
        f.write(body)
    cmd = ["qsub", "-cwd", "-t", f"1-{nproc}", "-b", "y", "-sync", "y"]
    if args.sge_queue:
        cmd += ["-q", args.sge_queue]
    cmd.append(script)
    log_info("sge: %s", " ".join(cmd))
    return subprocess.call(cmd)


def submit_mpi(args, tracker_envs: Dict[str, str]) -> int:
    nproc = args.num_workers + args.num_servers
    # OpenMPI vs MPICH rank env detection happens in the wrapper at runtime
    script = _wrapper_script(args, tracker_envs, "DMLC_MPI_RANK", "mpi")
    with open(script) as f:
        body = f.read().replace(
            'export DMLC_TASK_ID="${DMLC_MPI_RANK}"',
            'export DMLC_TASK_ID="${OMPI_COMM_WORLD_RANK:-${PMI_RANK:-0}}"')
    with open(script, "w") as f:
        f.write(body)
    cmd = ["mpirun", "-n", str(nproc)]
    if args.host_file:
        cmd += ["--hostfile", args.host_file]
    cmd.append(script)
    log_info("mpi: %s", " ".join(cmd))
    return subprocess.call(cmd)
