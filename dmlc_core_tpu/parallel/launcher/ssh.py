"""SSH launcher — capability parity with reference
``tracker/dmlc_tracker/ssh.py``: host-file parsing (`ssh.py:36-70`), optional
workdir rsync (`ssh.py:13-21`), per-host ssh spawn with env forwarding —
PLUS the YARN ApplicationMaster's container-replacement failure domain
(`ApplicationMaster.java:73-74,508,535-563`): a task that keeps dying on a
host is rescheduled onto another host from the host file, the dying host is
blacklisted, and the restarted task re-enters the tracker's ``recover``
path (same task id, bumped ``DMLC_NUM_ATTEMPT``) so surviving peers re-link
to its new address.  An unreachable host (ssh rc 255) is blacklisted on
first contact; otherwise a host is dropped after ``DMLC_HOST_FAIL_LIMIT``
(default 2) failures.  The job aborts once a task burns ``--max-attempts``
or no replacement host remains — the AM's maxNumAttempt abort.

Host file format: one ``host[:port]`` per line (the PHub fork's
``ip:interface:port`` interface pinning collapses to plain addressing here —
on TPU pods NIC selection is the platform's concern, not the launcher's)."""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List, Optional, Tuple

from ...utils import DMLCError, log_info, log_warning
from ...utils.parameter import env_int

__all__ = ["submit", "parse_host_file", "HostPool"]

_SSH_CONNECT_FAILED = 255  # ssh's own exit code for connection failure


class HostPool:
    """Host assignment with failure accounting and blacklisting (the node
    bookkeeping of the reference AM, `ApplicationMaster.java:535-563`)."""

    def __init__(self, hosts: List[Tuple[str, int]], fail_limit: int = 0):
        self._hosts = list(hosts)
        self._fail_limit = fail_limit or env_int(
            "DMLC_HOST_FAIL_LIMIT", 2, minimum=1)
        self._failures: Dict[Tuple[str, int], int] = {}
        self._black: set = set()
        self._next = 0
        self._lock = threading.Lock()

    def assign(self, exclude: Optional[Tuple[str, int]] = None
               ) -> Tuple[str, int]:
        """Next usable host round-robin; raises when none remain."""
        with self._lock:
            live = [h for h in self._hosts
                    if h not in self._black and h != exclude]
            if not live:
                raise DMLCError(
                    "no usable hosts remain (all blacklisted) — the AM "
                    "abort path, ApplicationMaster.java:508")
            h = live[self._next % len(live)]
            self._next += 1
            return h

    def record_failure(self, host: Tuple[str, int],
                       unreachable: bool = False) -> bool:
        """Count a task failure on ``host``; returns True when the host is
        now blacklisted."""
        with self._lock:
            n = self._failures[host] = self._failures.get(host, 0) + 1
            if unreachable or n >= self._fail_limit:
                if host not in self._black:
                    self._black.add(host)
                    log_warning("host %s:%d blacklisted after %d failure(s)%s",
                                host[0], host[1], n,
                                " (unreachable)" if unreachable else "")
                return True
            return False

    @property
    def blacklisted(self) -> set:
        with self._lock:
            return set(self._black)


def parse_host_file(path: str) -> List[Tuple[str, int]]:
    hosts: List[Tuple[str, int]] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if ":" in line:
                h, p = line.rsplit(":", 1)
                hosts.append((h, int(p)))
            else:
                hosts.append((line, 22))
    if not hosts:
        raise DMLCError(f"host file {path!r} lists no hosts")
    return hosts


def _env_exports(env: Dict[str, str]) -> str:
    return " ".join(f"{k}={_shquote(v)}" for k, v in env.items())


def _shquote(s: str) -> str:
    return "'" + s.replace("'", "'\"'\"'") + "'"


def submit(args, tracker_envs: Dict[str, str]) -> int:
    if not args.host_file:
        raise DMLCError("ssh cluster requires --host-file")
    hosts = parse_host_file(args.host_file)
    nproc = args.num_workers + args.num_servers
    workdir = os.getcwd()

    if args.sync_dst_dir:
        for host, port in set(hosts):
            log_info("rsync %s -> %s:%s", workdir, host, args.sync_dst_dir)
            subprocess.run(
                ["rsync", "-az", "-e", f"ssh -p {port}", workdir + "/",
                 f"{host}:{args.sync_dst_dir}/"], check=True)
        workdir = args.sync_dst_dir

    # --files/--archives + auto-cached command files: rsync to a staging
    # dir on every host and run the job there (no shared-FS assumption;
    # reference ships via the YARN file cache, yarn.py:35-42 — ssh's
    # equivalent is explicit per-host transfer)
    pool = HostPool(hosts)
    cache = (getattr(args, "cache_files", None) or []) + \
            (getattr(args, "cache_archives", None) or [])
    if cache:
        from uuid import uuid4
        from .filecache import unpack_command
        # per-submit unique dir: concurrent jobs (or two users) sharing a
        # host must not overwrite each other's shipped files
        stage = args.sync_dst_dir or (
            f"/tmp/dmlc_{args.jobname or 'job'}_{uuid4().hex[:8]}")
        ssh_base = ["ssh", "-o", "StrictHostKeyChecking=no"]
        for host, port in set(hosts):
            steps = [ssh_base + ["-p", str(port), host,
                                 f"mkdir -p {_shquote(stage)}"],
                     ["rsync", "-az", "-e", f"ssh -p {port}"] + cache
                     + [f"{host}:{stage}/"]]
            steps += [ssh_base + ["-p", str(port), host,
                                  f"cd {_shquote(stage)} && "
                                  f"{unpack_command(os.path.basename(a))}"]
                      for a in (getattr(args, "cache_archives", None) or [])]
            log_info("ship %d cached files -> %s:%s", len(cache), host, stage)
            for cmd in steps:
                rc = subprocess.call(cmd)
                if rc == _SSH_CONNECT_FAILED:
                    # host unreachable: blacklist it, tasks go elsewhere
                    log_warning("staging to %s:%d unreachable — blacklisting",
                                host, port)
                    pool.record_failure((host, port), unreachable=True)
                    break
                if rc != 0:
                    # a LOCAL/protocol error (bad source, perms, rsync exit
                    # 23) would hit every host the same way: abort loudly
                    # instead of blacklisting the fleet one by one
                    raise DMLCError(
                        f"file-cache staging failed (rc={rc}): "
                        f"{' '.join(cmd)}")
        workdir = stage
    max_attempts = max(1, getattr(args, "max_attempts", 1))
    results = [0] * nproc
    threads = []

    def supervise(slot: int) -> None:
        """Run one task with in-place retry + host replacement: stable task
        id across attempts (the rabit recover key), DMLC_NUM_ATTEMPT
        incremented, new host drawn from the pool when the current one is
        blacklisted (AM container replacement)."""
        role = "server" if slot < args.num_servers else "worker"
        env = dict(tracker_envs)
        env.update(args.extra_env)
        env.update({
            "DMLC_ROLE": role,
            "DMLC_TASK_ID": str(slot),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_JOB_CLUSTER": "ssh",
        })
        try:
            host, port = pool.assign()
        except DMLCError:
            results[slot] = 1
            return
        attempt = 0
        while attempt < max_attempts:
            env["DMLC_NUM_ATTEMPT"] = str(attempt)
            remote_cmd = (f"cd {_shquote(workdir)} && "
                          f"{_env_exports(env)} " +
                          " ".join(_shquote(c) for c in args.command))
            rc = subprocess.call(
                ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(port),
                 host, remote_cmd])
            if rc == 0:
                results[slot] = 0
                return
            results[slot] = rc
            unreachable = rc == _SSH_CONNECT_FAILED
            log_warning("ssh task %d on %s exited rc=%d (attempt %d/%d)",
                        slot, host, rc, attempt + 1, max_attempts)
            if not unreachable:
                # a connect failure never launched the task — it is a
                # placement failure, not a task attempt (the AM does not
                # count allocation failures against maxNumAttempt)
                attempt += 1
            if pool.record_failure((host, port), unreachable=unreachable):
                try:
                    host, port = pool.assign(exclude=(host, port))
                except DMLCError:
                    return  # no replacement host: abort with last rc
                if attempt < max_attempts:
                    log_info("ssh task %d rescheduled onto %s:%d",
                             slot, host, port)

    for i in range(nproc):
        t = threading.Thread(target=supervise, args=(i,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return next((rc for rc in results if rc), 0)
