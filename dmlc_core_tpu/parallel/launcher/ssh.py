"""SSH launcher — capability parity with reference
``tracker/dmlc_tracker/ssh.py``: host-file parsing (`ssh.py:36-70`), optional
workdir rsync (`ssh.py:13-21`), per-host ssh spawn with env forwarding.

Host file format: one ``host[:port]`` per line (the PHub fork's
``ip:interface:port`` interface pinning collapses to plain addressing here —
on TPU pods NIC selection is the platform's concern, not the launcher's)."""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List, Tuple

from ...utils import DMLCError, log_info, log_warning

__all__ = ["submit", "parse_host_file"]


def parse_host_file(path: str) -> List[Tuple[str, int]]:
    hosts: List[Tuple[str, int]] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if ":" in line:
                h, p = line.rsplit(":", 1)
                hosts.append((h, int(p)))
            else:
                hosts.append((line, 22))
    if not hosts:
        raise DMLCError(f"host file {path!r} lists no hosts")
    return hosts


def _env_exports(env: Dict[str, str]) -> str:
    return " ".join(f"{k}={_shquote(v)}" for k, v in env.items())


def _shquote(s: str) -> str:
    return "'" + s.replace("'", "'\"'\"'") + "'"


def submit(args, tracker_envs: Dict[str, str]) -> int:
    if not args.host_file:
        raise DMLCError("ssh cluster requires --host-file")
    hosts = parse_host_file(args.host_file)
    nproc = args.num_workers + args.num_servers
    workdir = os.getcwd()

    if args.sync_dst_dir:
        for host, port in set(hosts):
            log_info("rsync %s -> %s:%s", workdir, host, args.sync_dst_dir)
            subprocess.run(
                ["rsync", "-az", "-e", f"ssh -p {port}", workdir + "/",
                 f"{host}:{args.sync_dst_dir}/"], check=True)
        workdir = args.sync_dst_dir

    # --files/--archives + auto-cached command files: rsync to a staging
    # dir on every host and run the job there (no shared-FS assumption;
    # reference ships via the YARN file cache, yarn.py:35-42 — ssh's
    # equivalent is explicit per-host transfer)
    cache = (getattr(args, "cache_files", None) or []) + \
            (getattr(args, "cache_archives", None) or [])
    if cache:
        from uuid import uuid4
        from .filecache import unpack_command
        # per-submit unique dir: concurrent jobs (or two users) sharing a
        # host must not overwrite each other's shipped files
        stage = args.sync_dst_dir or (
            f"/tmp/dmlc_{args.jobname or 'job'}_{uuid4().hex[:8]}")
        ssh_base = ["ssh", "-o", "StrictHostKeyChecking=no"]
        for host, port in set(hosts):
            subprocess.run(ssh_base + ["-p", str(port), host,
                                       f"mkdir -p {_shquote(stage)}"],
                           check=True)
            log_info("ship %d cached files -> %s:%s", len(cache), host, stage)
            subprocess.run(["rsync", "-az", "-e", f"ssh -p {port}"] + cache
                           + [f"{host}:{stage}/"], check=True)
            for a in (getattr(args, "cache_archives", None) or []):
                unpack = unpack_command(os.path.basename(a))
                subprocess.run(ssh_base + ["-p", str(port), host,
                                           f"cd {_shquote(stage)} && {unpack}"],
                               check=True)
        workdir = stage

    results = [0] * nproc
    threads = []
    for i in range(nproc):
        host, port = hosts[i % len(hosts)]
        role = "server" if i < args.num_servers else "worker"
        env = dict(tracker_envs)
        env.update(args.extra_env)
        env.update({
            "DMLC_ROLE": role,
            "DMLC_TASK_ID": str(i),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_JOB_CLUSTER": "ssh",
        })
        remote_cmd = (f"cd {_shquote(workdir)} && "
                      f"{_env_exports(env)} " +
                      " ".join(_shquote(c) for c in args.command))
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(port),
               host, remote_cmd]

        def run(cmd=cmd, slot=i, host=host):
            rc = subprocess.call(cmd)
            results[slot] = rc
            if rc != 0:
                log_warning("ssh worker %d on %s exited rc=%d", slot, host, rc)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return next((rc for rc in results if rc), 0)
