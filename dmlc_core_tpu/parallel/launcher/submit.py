"""`dmlc-submit-tpu` entry point — capability parity with reference
``tracker/dmlc-submit`` + ``dmlc_tracker/submit.py``: boot the rendezvous
tracker, dispatch to the cluster backend, join until shutdown
(`submit.py:42-53`, `tracker.py:410-433`)."""

from __future__ import annotations

import sys
import threading
from typing import List, Optional

from ...utils import log_info
from ..tracker import RabitTracker
from .opts import get_opts

__all__ = ["main", "submit"]


def submit(argv: Optional[List[str]] = None) -> int:
    args = get_opts(argv)
    fh = None
    if args.log_file:
        # mirror launcher logs to a file, stderr stays on (reference
        # opts.py:98-100 --log-file); detached in the finally below so
        # repeated submit() calls don't accumulate handlers/fds
        import logging as _pylogging
        from ...utils.logging import get_logger
        fh = _pylogging.FileHandler(args.log_file)
        fh.setFormatter(_pylogging.Formatter(
            "[%(asctime)s] %(levelname)s %(message)s", "%H:%M:%S"))
        get_logger().addHandler(fh)
    try:
        return _submit_job(args)
    finally:
        if fh is not None:
            from ...utils.logging import get_logger
            get_logger().removeHandler(fh)
            fh.close()


def _submit_job(args) -> int:
    # a single-host job must rendezvous over loopback: the auto-detected
    # "routable" address may not be reachable from inside sandboxes/netns
    host_ip = args.host_ip or ("127.0.0.1" if args.cluster == "local"
                               else None)
    tracker = RabitTracker(num_workers=args.num_workers, host_ip=host_ip)
    tracker.start()
    envs = tracker.worker_envs()

    ps_tracker = None
    if args.num_servers > 0:
        # parameter-server mode: launch the user command locally as the
        # SCHEDULER (DMLC_ROLE=scheduler) and hand every process the same
        # rendezvous env — the reference passes the job command as pscmd
        # whenever nserver > 0 (reference local.py:72, tracker.py:410-425);
        # without a scheduler the PS root port has no listener and
        # server/worker rendezvous hangs
        from ..tracker import PSTracker
        ps_tracker = PSTracker(host_ip=host_ip or tracker.host_ip,
                               pscmd=list(args.command),
                               extra_env={
                                   "DMLC_NUM_WORKER": str(args.num_workers),
                                   "DMLC_NUM_SERVER": str(args.num_servers),
                                   **args.extra_env,
                               })
        envs.update(ps_tracker.worker_envs())

    if args.dry_run and args.cluster in ("local", "ssh", "tpu"):
        # direct-spawn backends have no scheduler command to preview:
        # show the resolved job spec and stop before launching anything
        # (incl. the PS scheduler — ps_tracker.start() runs user code)
        log_info("%s (dry run): %d workers + %d servers, env %s, cmd: %s",
                 args.cluster, args.num_workers, args.num_servers,
                 envs, " ".join(args.command))
        tracker.stop()
        if ps_tracker is not None:
            ps_tracker.stop()
        return 0

    if ps_tracker is not None:
        ps_tracker.start()

    if args.cluster == "local":
        from . import local as backend
        rc = backend.submit(args, envs)
    elif args.cluster == "ssh":
        from . import ssh as backend
        rc = backend.submit(args, envs)
    elif args.cluster == "slurm":
        from .batch import submit_slurm
        rc = submit_slurm(args, envs)
    elif args.cluster == "sge":
        from .batch import submit_sge
        rc = submit_sge(args, envs)
    elif args.cluster == "mpi":
        from .batch import submit_mpi
        rc = submit_mpi(args, envs)
    elif args.cluster == "yarn":
        from .yarn import submit_yarn
        rc = submit_yarn(args, envs)
    elif args.cluster == "mesos":
        from .mesos import submit_mesos
        rc = submit_mesos(args, envs)
    elif args.cluster == "tpu":
        from . import tpu as backend
        rc = backend.submit(args, envs)
    else:  # pragma: no cover - argparse restricts choices
        raise SystemExit(f"unknown cluster {args.cluster}")

    tracker.stop()
    if ps_tracker is not None:
        ps_tracker.stop()
    return rc


def main() -> None:
    sys.exit(submit())


if __name__ == "__main__":
    main()
