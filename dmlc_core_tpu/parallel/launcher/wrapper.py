"""Shared wrapper-script generation for scheduler-based launchers.

One home for the DMLC_* env contract so slurm/sge/mpi/yarn/mesos cannot
drift (reference equivalent: the env assembly in
``tracker/dmlc_tracker/tracker.py:410-433`` shared by every submit backend).
"""

from __future__ import annotations

import os
import shlex
import stat
import tempfile
from typing import Dict

__all__ = ["job_env", "render_exports", "retry_loop", "wrapper_body",
           "write_wrapper_script"]


def job_env(args, tracker_envs: Dict[str, str], cluster: str) -> Dict[str, str]:
    """The launch env contract common to every backend."""
    env = dict(tracker_envs)
    env.update(args.extra_env)
    env.update({
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_JOB_CLUSTER": cluster,
        "DMLC_MAX_ATTEMPT": str(args.max_attempts),
        # resource asks ride the env so role-aware runtimes can see them
        # (reference forwards worker/server cores+memory per role,
        # opts.py:85-90 → yarn AM container requests)
        "DMLC_WORKER_CORES": str(getattr(args, "worker_cores", 1)),
        "DMLC_WORKER_MEMORY_MB": str(getattr(args, "worker_memory_mb", 1024)),
        "DMLC_SERVER_CORES": str(getattr(args, "server_cores", 1)),
        "DMLC_SERVER_MEMORY_MB": str(getattr(args, "server_memory_mb", 1024)),
        "DMLC_HDFS_TEMPDIR": str(getattr(args, "hdfs_tempdir", "/tmp")),
    })
    return env


def render_exports(env: Dict[str, str]) -> str:
    return "\n".join(f"export {k}={shlex.quote(v)}" for k, v in env.items())


def retry_loop(cmd: str, *, oneline: bool = False) -> str:
    """The in-place retry protocol, shared by every scheduler backend: the
    task id (= rabit jobid) stays stable across attempts while
    ``DMLC_NUM_ATTEMPT`` increments, so on attempt > 0 the rabit client
    sends ``recover`` and the tracker re-issues the same rank with fresh
    neighbor addresses (``RabitContext.from_env`` + ``parallel.tracker``,
    the analog of reference `tracker.py:279-291` / the YARN AM's
    maxNumAttempt restart, `ApplicationMaster.java:210`)."""
    body = [
        f'DMLC_NUM_ATTEMPT="$attempt" {cmd}',
        'rc=$?',
        '[ "$rc" -eq 0 ] && exit 0',
        'attempt=$((attempt + 1))',
        'echo "dmlc: task ${DMLC_TASK_ID} exited rc=$rc'
        ' (attempt $attempt/${DMLC_MAX_ATTEMPT})" >&2',
        '[ "$attempt" -ge "${DMLC_MAX_ATTEMPT}" ] && exit "$rc"',
    ]
    if oneline:
        return f'attempt=0; while :; do {"; ".join(body)}; done'
    inner = "\n".join("  " + ln for ln in body)
    return f"attempt=0\nwhile :; do\n{inner}\ndone"


def wrapper_body(args, tracker_envs: Dict[str, str], cluster: str,
                 rank_snippet: str, stage_mode: str = "copy") -> str:
    """Wrapper shell body: export the env contract, run ``rank_snippet``
    (shell lines that must set ``DMLC_TASK_ID``), stage cached
    files/archives (``filecache.stage_snippet``; ``stage_mode='cwd'`` when
    the scheduler's own file cache already delivered them), derive
    ``DMLC_ROLE`` from the server split, then run the worker under
    :func:`retry_loop`.

    A missing, non-numeric, or out-of-range id fails fast with a clear
    message rather than joining the tracker with a bogus rank (in-place
    retry covers worker-process death; a scheduler that reschedules the
    whole task re-runs this wrapper and recovers through the same
    stable-id path)."""
    from .filecache import stage_snippet
    exports = render_exports(job_env(args, tracker_envs, cluster))
    cmd = " ".join(shlex.quote(c) for c in args.command)
    staging = stage_snippet(getattr(args, "cache_files", None) or [],
                            getattr(args, "cache_archives", None) or [],
                            mode=stage_mode)
    ns = args.num_servers
    nproc = args.num_workers + args.num_servers
    return f"""#!/bin/bash
{exports}
{rank_snippet}
{staging}
case "${{DMLC_TASK_ID}}" in
  (''|*[!0-9]*)
    echo "dmlc wrapper: task id '${{DMLC_TASK_ID}}' is not a number" >&2
    exit 1;;
esac
# supervisor-side node blacklist (yarn_am: REST submissions cannot carry
# an explicit node exclusion, so the wrapper enforces it — landing on a
# blacklisted node fails fast and the retry places elsewhere)
if [ -n "${{DMLC_BLACKLISTED_NODES:-}}" ]; then
  case ",${{DMLC_BLACKLISTED_NODES}}," in
    (*",$(hostname -s),"*|*",$(hostname -f 2>/dev/null || hostname),"*)
      echo "dmlc wrapper: node $(hostname) is blacklisted — exiting" >&2
      exit 1;;
  esac
fi
if [ "${{DMLC_TASK_ID}}" -ge "{nproc}" ]; then
  echo "dmlc wrapper: task id '${{DMLC_TASK_ID}}' outside cohort of {nproc}" >&2
  exit 1
fi
if [ "${{DMLC_TASK_ID}}" -lt "{ns}" ]; then
  export DMLC_ROLE=server
else
  export DMLC_ROLE=worker
fi
{retry_loop(cmd)}
"""


def write_wrapper_script(args, tracker_envs: Dict[str, str], cluster: str,
                         rank_snippet: str, stage_mode: str = "copy") -> str:
    """Write :func:`wrapper_body` to an executable temp file."""
    body = wrapper_body(args, tracker_envs, cluster, rank_snippet, stage_mode)
    fd, path = tempfile.mkstemp(prefix=f"dmlc_{cluster}_", suffix=".sh")
    with os.fdopen(fd, "w") as f:
        f.write(body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR)
    return path
