"""Shared wrapper-script generation for scheduler-based launchers.

One home for the DMLC_* env contract so slurm/sge/mpi/yarn/mesos cannot
drift (reference equivalent: the env assembly in
``tracker/dmlc_tracker/tracker.py:410-433`` shared by every submit backend).
"""

from __future__ import annotations

import os
import shlex
import stat
import tempfile
from typing import Dict

__all__ = ["job_env", "render_exports", "write_wrapper_script"]


def job_env(args, tracker_envs: Dict[str, str], cluster: str) -> Dict[str, str]:
    """The launch env contract common to every backend."""
    env = dict(tracker_envs)
    env.update(args.extra_env)
    env.update({
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": str(args.num_servers),
        "DMLC_JOB_CLUSTER": cluster,
        "DMLC_MAX_ATTEMPT": str(args.max_attempts),
    })
    return env


def render_exports(env: Dict[str, str]) -> str:
    return "\n".join(f"export {k}={shlex.quote(v)}" for k, v in env.items())


def write_wrapper_script(args, tracker_envs: Dict[str, str], cluster: str,
                         rank_snippet: str) -> str:
    """Write an executable wrapper that exports the env contract, runs
    ``rank_snippet`` (shell lines that must set ``DMLC_TASK_ID``), derives
    ``DMLC_ROLE`` from the server split, and execs the worker command."""
    exports = render_exports(job_env(args, tracker_envs, cluster))
    cmd = " ".join(shlex.quote(c) for c in args.command)
    ns = args.num_servers
    nproc = args.num_workers + args.num_servers
    body = f"""#!/bin/bash
{exports}
{rank_snippet}
if [ -n "${{DMLC_TASK_ID}}" ] && [ "${{DMLC_TASK_ID}}" -ge 0 ] \\
   && [ "${{DMLC_TASK_ID}}" -lt "{nproc}" ]; then
  if [ "${{DMLC_TASK_ID}}" -lt "{ns}" ]; then
    export DMLC_ROLE=server
  else
    export DMLC_ROLE=worker
  fi
else
  # unknown/out-of-range id (e.g. a scheduler-restarted container):
  # let the tracker assign a recovered rank instead of trusting the id
  unset DMLC_TASK_ID
  export DMLC_ROLE=worker
  export DMLC_RECOVER=1
fi
exec {cmd}
"""
    fd, path = tempfile.mkstemp(prefix=f"dmlc_{cluster}_", suffix=".sh")
    with os.fdopen(fd, "w") as f:
        f.write(body)
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR)
    return path
