"""CLI options for the job launcher — capability parity with reference
``tracker/dmlc_tracker/opts.py`` (`opts.py:60-163`)."""

from __future__ import annotations

import argparse
import os
from typing import List, Optional

__all__ = ["build_parser", "get_opts"]

CLUSTERS = ["local", "ssh", "mpi", "sge", "slurm", "yarn", "mesos", "tpu"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dmlc-submit-tpu",
        description="Submit a distributed job (TPU-native dmlc-submit): "
                    "boots a rendezvous tracker and launches workers on the "
                    "chosen cluster backend.")
    p.add_argument("--cluster", default=os.environ.get(
        "DMLC_SUBMIT_CLUSTER", "local"), choices=CLUSTERS,
        help="cluster backend (env DMLC_SUBMIT_CLUSTER overrides the default)")
    p.add_argument("--num-workers", "-n", type=int, required=True,
                   help="number of worker processes")
    p.add_argument("--num-servers", "-s", type=int, default=0,
                   help="number of server processes (parameter-server mode)")
    p.add_argument("--worker-cores", type=int, default=1)
    p.add_argument("--worker-memory-mb", type=int, default=1024)
    p.add_argument("--jobname", default=None)
    p.add_argument("--host-file", default=None,
                   help="ssh/mpi: file listing one host per line")
    p.add_argument("--host-ip", default=None,
                   help="tracker bind address (default: auto-detect)")
    p.add_argument("--sync-dst-dir", default=None,
                   help="ssh: rsync the working dir to this path on each host")
    p.add_argument("--slurm-partition", default=None)
    p.add_argument("--sge-queue", default=None)
    p.add_argument("--yarn-queue", default=None,
                   help="yarn: capacity-scheduler queue")
    p.add_argument("--mesos-master", default=None,
                   help="mesos: master host:port (env MESOS_MASTER)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the scheduler submission without running it")
    p.add_argument("--max-attempts", type=int,
                   default=int(os.environ.get("DMLC_MAX_ATTEMPT", "3")),
                   help="per-worker restart attempts before giving up")
    p.add_argument("--env", action="append", default=[],
                   metavar="K=V", help="extra env vars forwarded to workers")
    p.add_argument("--log-level", default="INFO")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command line")
    return p


def get_opts(argv: Optional[List[str]] = None) -> argparse.Namespace:
    args = build_parser().parse_args(argv)
    if not args.command:
        build_parser().error("no worker command given")
    # strip a leading '--' separator
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    args.extra_env = {}
    for kv in args.env:
        if "=" not in kv:
            build_parser().error(f"--env expects K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        args.extra_env[k] = v
    return args
