"""CLI options for the job launcher — capability parity with reference
``tracker/dmlc_tracker/opts.py`` (`opts.py:60-163`)."""

from __future__ import annotations

import argparse
import os
from typing import List, Optional
from ...utils.parameter import env_int, get_env, parse_lenient_bool

__all__ = ["build_parser", "get_opts"]

CLUSTERS = ["local", "ssh", "mpi", "sge", "slurm", "yarn", "mesos", "tpu"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dmlc-submit-tpu",
        description="Submit a distributed job (TPU-native dmlc-submit): "
                    "boots a rendezvous tracker and launches workers on the "
                    "chosen cluster backend.")
    p.add_argument("--cluster", default=get_env(
        "DMLC_SUBMIT_CLUSTER", "local"), choices=CLUSTERS,
        help="cluster backend (env DMLC_SUBMIT_CLUSTER overrides the default)")
    p.add_argument("--num-workers", "-n", type=int, required=True,
                   help="number of worker processes")
    p.add_argument("--num-servers", "-s", type=int, default=0,
                   help="number of server processes (parameter-server mode)")
    p.add_argument("--worker-cores", type=int, default=1)
    p.add_argument("--worker-memory-mb", type=int, default=1024)
    p.add_argument("--worker-memory", default=None, metavar="Ng|Nm",
                   help="worker memory as '4g'/'512m' (reference form; "
                        "overrides --worker-memory-mb)")
    p.add_argument("--server-cores", type=int, default=1,
                   help="cores per server process (PS mode)")
    p.add_argument("--server-memory-mb", type=int, default=1024)
    p.add_argument("--server-memory", default=None, metavar="Ng|Nm",
                   help="server memory as '4g'/'512m' (overrides "
                        "--server-memory-mb)")
    p.add_argument("--jobname", default=None)
    p.add_argument("--log-file", default=None,
                   help="also write launcher logs to this file "
                        "(stderr logging stays on)")
    p.add_argument("--hdfs-tempdir", default="/tmp",
                   help="HDFS temp dir, exported to workers as "
                        "DMLC_HDFS_TEMPDIR (reference opts.py:104; its "
                        "yarn client staged job files through it)")
    p.add_argument("--sge-log-dir", default=None,
                   help="sge: directory for qsub stdout/stderr logs")
    p.add_argument("--files", action="append", default=[], metavar="PATH",
                   help="ship this file into each worker's cwd "
                        "(repeatable)")
    p.add_argument("--archives", action="append", default=[], metavar="PATH",
                   help="ship and extract this zip/tar into each worker's "
                        "cwd (repeatable)")
    p.add_argument("--auto-file-cache", default=None,
                   type=lambda s: s.lower() not in ("0", "false", "no"),
                   help="auto-ship command-line tokens that name local "
                        "files under the cwd, rewriting them to ./<name>. "
                        "Default: on for yarn (the executable must ship, "
                        "as the reference does) and whenever "
                        "--files/--archives are given; off otherwise, so "
                        "in-place jobs keep their cwd-relative paths")
    p.add_argument("--host-file", default=None,
                   help="ssh/mpi: file listing one host per line")
    p.add_argument("--host-ip", default=None,
                   help="tracker bind address (default: auto-detect)")
    p.add_argument("--sync-dst-dir", default=None,
                   help="ssh: rsync the working dir to this path on each host")
    p.add_argument("--queue", default=None,
                   help="scheduler queue (reference opts.py:96); maps to "
                        "the backend-specific queue unless that is set "
                        "explicitly (--sge-queue/--yarn-queue/"
                        "--slurm-partition)")
    p.add_argument("--slurm-partition", default=None)
    p.add_argument("--slurm-worker-nodes", type=int, default=None,
                   help="slurm: node count for the worker srun "
                        "(reference opts.py --slurm-worker-nodes)")
    p.add_argument("--slurm-server-nodes", type=int, default=None,
                   help="slurm: node count for the server srun")
    p.add_argument("--sge-queue", default=None)
    p.add_argument("--yarn-queue", default=None,
                   help="yarn: capacity-scheduler queue")
    p.add_argument("--yarn-app-classpath", default=None,
                   help="yarn: extra classpath exported to containers as "
                        "DMLC_YARN_APP_CLASSPATH (reference opts.py:118)")
    p.add_argument("--yarn-app-dir", default=None,
                   help="yarn: staging dir for shipped job files "
                        "(reference yarn.py jar/app dir)")
    p.add_argument("--mesos-master", default=None,
                   help="mesos: master host:port (env MESOS_MASTER)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the scheduler submission without running it")
    p.add_argument("--max-attempts", type=int,
                   default=env_int("DMLC_MAX_ATTEMPT", 3, minimum=1),
                   help="per-worker restart attempts before giving up")
    p.add_argument("--elastic", action="store_true",
                   default=bool(parse_lenient_bool("DMLC_ELASTIC")),
                   help="tpu cluster: respawn crashed workers with a "
                        "bumped DMLC_NUM_ATTEMPT (pair worker code with "
                        "ElasticJaxMesh — plain jax.distributed cannot "
                        "admit a reborn process, so without elastic "
                        "worker code a respawn would hang, which is why "
                        "this is opt-in)")
    p.add_argument("--env", action="append", default=[],
                   metavar="K=V", help="extra env vars forwarded to workers")
    p.add_argument("--log-level", default="INFO")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command line")
    return p


def memory_mb(mem: str) -> int:
    """'4g'/'512m' → MB (reference ``opts.py:get_memory_mb``)."""
    m = mem.lower()
    if m.endswith("g"):
        return int(float(m[:-1]) * 1024)
    if m.endswith("m"):
        return int(float(m[:-1]))
    raise ValueError(f"memory spec {mem!r} must end with 'g' or 'm'")


def get_opts(argv: Optional[List[str]] = None) -> argparse.Namespace:
    args = build_parser().parse_args(argv)
    if not args.command:
        build_parser().error("no worker command given")
    # strip a leading '--' separator
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    args.extra_env = {}
    for kv in args.env:
        if "=" not in kv:
            build_parser().error(f"--env expects K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        args.extra_env[k] = v
    # generic --queue (reference name) maps onto whichever backend queue
    # wasn't given explicitly
    if args.queue:
        args.sge_queue = args.sge_queue or args.queue
        args.yarn_queue = args.yarn_queue or args.queue
        args.slurm_partition = args.slurm_partition or args.queue
    if args.yarn_app_dir:
        args.extra_env.setdefault("DMLC_YARN_APP_DIR", args.yarn_app_dir)
    for which in ("worker", "server"):
        spec = getattr(args, f"{which}_memory")
        if spec is not None:
            try:
                setattr(args, f"{which}_memory_mb", memory_mb(spec))
            except ValueError as e:
                build_parser().error(str(e))
    # --files/--archives must exist NOW: a typo'd path should fail the
    # submit, not surface as FileNotFoundError inside a worker later
    for f in args.files + args.archives:
        if not os.path.exists(f):
            build_parser().error(f"--files/--archives path not found: {f!r}")
    # file cache: auto-ship command files + --files/--archives, rewrite the
    # command to staged names (reference get_cache_file_set, opts.py:6-36).
    # The rewrite moves the worker cwd to a staging dir, so it only engages
    # when shipping is actually in play — explicitly shipped files, yarn
    # (whose containers never share the submit cwd), or an explicit
    # --auto-file-cache true
    if args.auto_file_cache is None:
        args.auto_file_cache = bool(args.files or args.archives
                                    or args.cluster == "yarn")
    from .filecache import resolve
    args.command_raw = list(args.command)
    args.cache_files, args.cache_archives, args.command = resolve(
        args.command, args.files, args.archives, args.auto_file_cache)
    return args
