"""Cluster launchers (reference ``tracker/dmlc_tracker`` SURVEY §2.5):
local / ssh / slurm / sge / mpi / tpu backends behind one submit CLI."""

from .opts import build_parser, get_opts  # noqa: F401
from .submit import submit, main  # noqa: F401

__all__ = ["build_parser", "get_opts", "submit", "main"]
