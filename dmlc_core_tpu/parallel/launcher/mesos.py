"""Mesos launcher — capability parity with reference
``tracker/dmlc_tracker/mesos.py``.

The reference submits one task per worker either through pymesos or by
shelling out to ``mesos-execute`` (`mesos.py:16-50`). pymesos is not in this
image, so the ``mesos-execute`` path is the implementation. The full env
contract and worker command are **inlined into the ``--command`` string**
(``mesos-execute`` does not ship local files to agents, so a wrapper script
on the submitting host would not exist on the agent); ``DMLC_TASK_ID`` is
baked per task exactly as the reference builds one TaskInfo per rank.

``--files``/``--archives`` on this backend assume the submit-host paths are
reachable from the agents over a shared filesystem (same assumption as the
slurm/sge wrappers); the inlined staging aborts the attempt loudly if the
copy fails rather than running in an empty scratch dir.
"""

from __future__ import annotations

import os
import shlex
import subprocess
from typing import Dict, List

from ...utils import DMLCError, log_info
from .wrapper import job_env, retry_loop

__all__ = ["submit_mesos", "build_mesos_commands"]


def _inline_command(args, tracker_envs: Dict[str, str], task_id: int) -> str:
    from .filecache import stage_snippet
    env = job_env(args, tracker_envs, "mesos")
    env["DMLC_TASK_ID"] = str(task_id)
    env["DMLC_ROLE"] = ("server" if task_id < args.num_servers else "worker")
    exports = "; ".join(f"export {k}={shlex.quote(v)}"
                        for k, v in env.items())
    staging = stage_snippet(getattr(args, "cache_files", None) or [],
                            getattr(args, "cache_archives", None) or [])
    staging = staging.replace("\n", "; ") + "; " if staging else ""
    cmd = " ".join(shlex.quote(c) for c in args.command)
    return f"{exports}; {staging}{retry_loop(cmd, oneline=True)}"


def build_mesos_commands(args, tracker_envs: Dict[str, str]) -> List[List[str]]:
    """One ``mesos-execute`` invocation per task (reference `mesos.py:16-50`)."""
    master = (getattr(args, "mesos_master", None)
              or os.environ.get("MESOS_MASTER", "127.0.0.1:5050"))
    nproc = args.num_workers + args.num_servers
    cmds = []
    for tid in range(nproc):
        name = f"{args.jobname or 'dmlc'}-task-{tid}"
        cmds.append([
            "mesos-execute",
            f"--master={master}",
            f"--name={name}",
            f"--command={_inline_command(args, tracker_envs, tid)}",
            f"--resources=cpus:{args.worker_cores};"
            f"mem:{args.worker_memory_mb}",
        ])
    return cmds


def submit_mesos(args, tracker_envs: Dict[str, str]) -> int:
    cmds = build_mesos_commands(args, tracker_envs)
    if args.dry_run:
        for c in cmds:
            log_info("mesos (dry run): %s", " ".join(c))
        return 0
    procs = []
    try:
        for c in cmds:
            log_info("mesos: %s", " ".join(c))
            procs.append(subprocess.Popen(c))
    except OSError as e:
        # any mid-loop spawn failure (missing binary, EMFILE, perms) must
        # not leak the tasks already submitted
        for p in procs:
            p.terminate()
        raise DMLCError(f"mesos submit failed: {e}") from e
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc
