"""Container-granularity YARN supervision over the RM REST API.

The reference ships a custom Java ApplicationMaster whose failure policy is
(`/root/reference/tracker/yarn/src/main/java/org/apache/hadoop/yarn/dmlc/
ApplicationMaster.java:535-563`): when a container completes abnormally,
count the failure against its node (blacklist the node past a threshold),
re-request a replacement container for THAT task only, and abort the whole
job once a task exceeds ``maxNumAttempt`` (`:73-74`, abort `:508`).

Re-requesting containers inside a running application needs the AM↔RM
protobuf protocol (what the Java AM links against).  The TPU-native
re-expression keeps the same failure domain without any Java: **one
single-container application per task**, driven entirely through the RM
REST API (``/ws/v1/cluster/apps``).  An "application" here is exactly one
container (the AM container runs the task command itself — YARN's
AM-only-app pattern), so

* container death        == one app finishing FAILED → resubmit ONLY that
  task's app with ``DMLC_NUM_ATTEMPT`` bumped (the stable task id flips the
  rabit client into ``recover``, same as every other launcher);
* node blacklisting      == supervisor-side failure counts per node
  (from the report's ``amHostHttpAddress``); blacklisted nodes ride
  ``DMLC_BLACKLISTED_NODES`` into the wrapper, which fails fast when it
  lands on one (YARN then places the retry elsewhere — REST submissions
  cannot carry an explicit node blacklist, so the wrapper enforces it),
  and ``am-black-listing-requests`` turns on YARN's own AM blacklisting;
* abort-after-max        == one task exhausting ``max_attempts`` kills
  every still-running task app and fails the job (reference ``:508``).

The decision logic lives in :class:`TaskSupervisor`, dependency-injected
over :class:`YarnRestClient` so tests drive it against a fake RM
(tests/test_launchers.py) — a container death is proven to retry without
touching the other tasks' applications.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional

from ...utils import DMLCError, log_info, log_warning
from ...utils.parameter import env_int, get_env

__all__ = ["YarnRestClient", "TaskSpec", "TaskSupervisor"]

_FINAL_STATES = {"FINISHED", "FAILED", "KILLED"}


class YarnRestClient:
    """Thin JSON client for the RM's app lifecycle REST endpoints."""

    def __init__(self, rm_http: str, timeout: float = 10.0) -> None:
        if not rm_http:
            raise DMLCError("yarn REST mode needs DMLC_YARN_RM_HTTP "
                            "(http://rm-host:8088)")
        self.rm = rm_http.rstrip("/")
        self.timeout = timeout

    def _req(self, method: str, path: str,
             payload: Optional[dict] = None) -> dict:
        import urllib.request
        body = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            f"{self.rm}{path}", data=body, method=method,
            headers={"Content-Type": "application/json"} if body else {})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            data = r.read()
        return json.loads(data.decode()) if data.strip() else {}

    def new_application(self) -> str:
        out = self._req("POST", "/ws/v1/cluster/apps/new-application")
        app_id = out.get("application-id", "")
        if not app_id:
            raise DMLCError(f"new-application returned no id: {out}")
        return app_id

    def submit(self, payload: dict) -> None:
        self._req("POST", "/ws/v1/cluster/apps", payload)

    def report(self, app_id: str) -> dict:
        return self._req("GET", f"/ws/v1/cluster/apps/{app_id}").get(
            "app", {}) or {}

    def kill(self, app_id: str) -> None:
        try:
            self._req("PUT", f"/ws/v1/cluster/apps/{app_id}/state",
                      {"state": "KILLED"})
        except Exception as e:  # noqa: BLE001 — best-effort teardown
            log_warning("yarn: kill %s failed (%s)", app_id, e)


class TaskSpec:
    """One task == one single-container application."""

    def __init__(self, task_id: int, command: str,
                 env: Optional[Dict[str, str]] = None,
                 memory_mb: int = 1024, vcores: int = 1,
                 queue: str = "", name: str = "") -> None:
        self.task_id = task_id
        self.command = command
        self.env = dict(env or {})
        self.memory_mb = memory_mb
        self.vcores = vcores
        self.queue = queue
        self.name = name or f"dmlc-task-{task_id}"


def _node_of(report: dict) -> str:
    """Node a finished app's (only) container ran on: host part of
    ``amHostHttpAddress`` (the AM container IS the task container)."""
    host = report.get("amHostHttpAddress", "") or report.get("amHost", "")
    return host.split(":")[0]


class TaskSupervisor:
    """The reference AM's failure policy over per-task REST applications.

    Parameters mirror the Java AM's knobs: ``max_attempts`` ==
    ``DMLC_MAX_ATTEMPT`` (`ApplicationMaster.java:73`), ``node_fail_limit``
    == the per-node blacklist threshold (`:74` maxFailedOnNode).  ``sleep``
    is injectable so the fake-RM test runs in milliseconds.
    """

    def __init__(self, client: YarnRestClient, tasks: List[TaskSpec], *,
                 max_attempts: int = 3, node_fail_limit: int = 3,
                 poll_s: float = 2.0,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.client = client
        self.tasks = {t.task_id: t for t in tasks}
        self.max_attempts = max(1, int(max_attempts))
        self.node_fail_limit = max(1, int(node_fail_limit))
        self.poll_s = poll_s
        self.sleep = sleep
        self.attempts: Dict[int, int] = {t.task_id: 0 for t in tasks}
        self.app_of: Dict[int, str] = {}          # running task -> app id
        self.done: Dict[int, str] = {}            # task -> final app id
        self.node_failures: Dict[str, int] = {}
        self.blacklist: set = set()
        self.submitted_payloads: List[dict] = []  # telemetry/testability
        self._pending_submit: List[int] = []      # tasks awaiting (re)submit
        self._reserved_app: Dict[int, str] = {}   # task -> unconfirmed app id

    # -- submission -------------------------------------------------------
    def _payload(self, t: TaskSpec, app_id: str) -> dict:
        env = dict(t.env)
        env["DMLC_TASK_ID"] = str(t.task_id)
        env["DMLC_NUM_ATTEMPT"] = str(self.attempts[t.task_id])
        env["DMLC_MAX_ATTEMPT"] = str(self.max_attempts)
        if self.blacklist:
            env["DMLC_BLACKLISTED_NODES"] = ",".join(sorted(self.blacklist))
        p = {
            "application-id": app_id,
            "application-name": t.name,
            "application-type": "DMLC",
            "am-container-spec": {
                "commands": {"command": t.command},
                "environment": {"entry": [
                    {"key": k, "value": v} for k, v in sorted(env.items())]},
            },
            "resource": {"memory": t.memory_mb, "vCores": t.vcores},
            # the app-attempt layer retries AM (==container) crashes YARN-
            # side too; the supervisor still counts/aborts at task level
            "max-app-attempts": 1,
            "am-black-listing-requests": {
                "am-black-listing-enabled": True,
                "disable-failure-threshold": 0.5},
        }
        if t.queue:
            p["queue"] = t.queue
        return p

    def _submit_task(self, t: TaskSpec) -> None:
        """Submit (or resubmit) one task's app.  A transient RM error must
        not crash the supervisor mid-job (the RM REST endpoint blips
        during failovers; ``rm_app_report`` degrades the same way): the
        task parks in ``_pending_submit`` and retries next poll tick.

        The app id is reserved BEFORE the submit and remembered across
        retries: a submit whose RESPONSE is lost (RM accepted, our read
        timed out) must not resubmit under a fresh id — that launches the
        same task twice, with the first copy running unsupervised.  On
        retry we first ask the RM whether the reserved id already exists
        and adopt it if so."""
        try:
            app_id = self._reserved_app.get(t.task_id)
            if app_id is None:
                app_id = self.client.new_application()
                self._reserved_app[t.task_id] = app_id
            else:
                try:
                    landed = bool(self.client.report(app_id).get("state"))
                except Exception:  # noqa: BLE001 — RM has no such app
                    landed = False
                if landed:
                    log_info("yarn: task %d submit of %s had landed — "
                             "adopting, not resubmitting", t.task_id, app_id)
                    self._reserved_app.pop(t.task_id, None)
                    self.app_of[t.task_id] = app_id
                    return
            payload = self._payload(t, app_id)
            self.client.submit(payload)
        except Exception as e:  # noqa: BLE001 — RM blip, retry next tick
            log_warning("yarn: submit of task %d failed (%s: %s) — "
                        "will retry", t.task_id, type(e).__name__, e)
            if t.task_id not in self._pending_submit:
                self._pending_submit.append(t.task_id)
            return
        self._reserved_app.pop(t.task_id, None)
        self.submitted_payloads.append(payload)
        self.app_of[t.task_id] = app_id
        log_info("yarn: task %d attempt %d → %s", t.task_id,
                 self.attempts[t.task_id], app_id)

    # -- failure policy (ApplicationMaster.java:535-563) ------------------
    def _on_failure(self, task_id: int, report: dict) -> bool:
        """Count, blacklist, retry-or-abort.  Returns False to abort."""
        node = _node_of(report)
        if node:
            n = self.node_failures[node] = self.node_failures.get(node, 0) + 1
            if n >= self.node_fail_limit and node not in self.blacklist:
                self.blacklist.add(node)
                log_warning("yarn: node %s blacklisted after %d failures",
                            node, n)
        self.attempts[task_id] += 1
        diag = (report.get("diagnostics") or "").strip()[:300]
        log_warning("yarn: task %d failed on %s (attempt %d/%d)%s",
                    task_id, node or "?", self.attempts[task_id],
                    self.max_attempts, f": {diag}" if diag else "")
        if self.attempts[task_id] >= self.max_attempts:
            # reference aborts the whole job when one task exhausts its
            # attempts (`:508` onCompleted(FAILED) path)
            log_warning("yarn: task %d exceeded max attempts — aborting job",
                        task_id)
            return False
        self._submit_task(self.tasks[task_id])
        return True

    def _abort(self) -> None:
        for tid, app_id in list(self.app_of.items()):
            log_info("yarn: killing task %d (%s)", tid, app_id)
            self.client.kill(app_id)
        self.app_of.clear()

    # -- main loop --------------------------------------------------------
    def run(self) -> int:
        """Submit every task, supervise to completion.  0 iff all tasks'
        apps finish SUCCEEDED; 1 on abort (a task over max_attempts).
        Transient RM REST errors (poll or submit) degrade to a warning
        and a retry next tick — a supervisor that dies on an RM blip
        would orphan every running app unsupervised."""
        for t in self.tasks.values():
            self._submit_task(t)
        while self.app_of or self._pending_submit:
            for task_id in self._pending_submit[:]:
                self._pending_submit.remove(task_id)
                self._submit_task(self.tasks[task_id])
            for task_id, app_id in list(self.app_of.items()):
                try:
                    report = self.client.report(app_id)
                except Exception as e:  # noqa: BLE001 — RM blip
                    log_warning("yarn: poll of %s failed (%s: %s) — "
                                "retrying next tick", app_id,
                                type(e).__name__, e)
                    continue
                state = report.get("state", "")
                if state not in _FINAL_STATES:
                    continue
                del self.app_of[task_id]
                if (state == "FINISHED"
                        and report.get("finalStatus") == "SUCCEEDED"):
                    self.done[task_id] = app_id
                    log_info("yarn: task %d finished (%s)", task_id, app_id)
                elif state == "KILLED":
                    # only _abort() kills our apps, and it never returns to
                    # this loop — so KILLED means an operator/preemption
                    # outside the supervisor.  That is job-level intent,
                    # not a container fault: abort without counting a node
                    # failure (a kill must not blacklist a healthy node)
                    log_warning("yarn: task %d app %s killed externally — "
                                "aborting job", task_id, app_id)
                    self._abort()
                    return 1
                elif not self._on_failure(task_id, report):
                    self._abort()
                    return 1
            if self.app_of or self._pending_submit:
                self.sleep(self.poll_s)
        return 0


def supervise_from_args(args, tracker_envs: Dict[str, str]) -> int:
    """Entry used by submit_yarn's REST mode: build per-task specs from the
    launcher args (same wrapper body as every backend, shipped inline via
    base64 — REST submissions have no file cache) and run the supervisor."""
    import base64

    from .wrapper import wrapper_body

    # task id arrives via env (the supervisor sets it per app); the rank
    # snippet just re-exports it so the shared wrapper's validation runs
    body = wrapper_body(args, tracker_envs, "yarn",
                        'export DMLC_TASK_ID="${DMLC_TASK_ID}"',
                        stage_mode="copy")
    blob = base64.b64encode(body.encode()).decode()
    command = (f"echo {blob} | base64 -d > dmlc_task.sh && "
               f"exec bash dmlc_task.sh")
    nproc = args.num_workers + args.num_servers
    tasks = [TaskSpec(
        i, command,
        memory_mb=(args.server_memory_mb if i < args.num_servers
                   else args.worker_memory_mb),
        vcores=(args.server_cores if i < args.num_servers
                else args.worker_cores),
        queue=getattr(args, "yarn_queue", "") or "",
        name=f"{args.jobname or 'dmlc'}-task{i}") for i in range(nproc)]
    client = YarnRestClient(get_env("DMLC_YARN_RM_HTTP", ""))
    sup = TaskSupervisor(
        client, tasks,
        max_attempts=max(1, getattr(args, "max_attempts", 1)),
        node_fail_limit=env_int("DMLC_YARN_NODE_FAIL_LIMIT", 3,
                                minimum=1))
    return sup.run()
