"""Local multi-process launcher — capability parity with reference
``tracker/dmlc_tracker/local.py``: N subprocesses on this host, each with the
DMLC_* env contract and a retry loop honoring ``DMLC_NUM_ATTEMPT``
(`local.py:12-44`)."""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
from typing import Dict, List, Optional

from ...utils import log_info, log_warning

__all__ = ["submit"]


def _run_with_retry(cmd: List[str], env: Dict[str, str], max_attempts: int,
                    results: List[int], slot: int,
                    cwd: Optional[str] = None) -> None:
    attempt = 0
    while True:
        env_try = dict(env, DMLC_NUM_ATTEMPT=str(attempt))
        proc = subprocess.Popen(cmd, env=env_try, cwd=cwd)
        rc = proc.wait()
        if rc == 0:
            results[slot] = 0
            return
        attempt += 1
        log_warning("worker %s exited rc=%d (attempt %d/%d)",
                    env.get("DMLC_TASK_ID"), rc, attempt, max_attempts)
        if attempt >= max_attempts:
            results[slot] = rc
            return


def submit(args, tracker_envs: Dict[str, str]) -> int:
    """Spawn workers+servers locally; returns first nonzero exit code or 0."""
    nproc = args.num_workers + args.num_servers
    # ship --files/--archives + auto-cached command files into a job
    # staging dir and run the workers there (reference YARN file-cache
    # semantics, yarn.py:35-42, expressed as a local cwd)
    stage_dir = None
    if getattr(args, "cache_files", None) or getattr(args, "cache_archives",
                                                     None):
        from .filecache import stage_into
        stage_dir = tempfile.mkdtemp(prefix="dmlc_stage_")
        stage_into(stage_dir, args.cache_files, args.cache_archives)
        log_info("staged %d files + %d archives into %s",
                 len(args.cache_files), len(args.cache_archives), stage_dir)
    threads = []
    results = [0] * nproc
    for i in range(nproc):
        role = "server" if i < args.num_servers else "worker"
        env = dict(os.environ)
        env.update(tracker_envs)
        env.update(args.extra_env)
        env.update({
            "DMLC_ROLE": role,
            "DMLC_TASK_ID": str(i),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": str(args.num_servers),
            "DMLC_JOB_CLUSTER": "local",
        })
        t = threading.Thread(
            target=_run_with_retry,
            args=(args.command, env, max(1, args.max_attempts), results, i,
                  stage_dir),
            daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    bad = [rc for rc in results if rc != 0]
    if bad:
        log_warning("local job finished with failures: %s", results)
        return bad[0]
    log_info("local job finished: all %d processes exited cleanly", nproc)
    return 0
