"""Elastic rejoin for the JAX process mesh (SURVEY §7 hard part (c)).

``jax.distributed`` has no native elasticity: one dead process wedges every
collective in its generation, and the coordination service cannot admit a
late joiner into a running cohort.  The reference faces the same problem
for rabit and solves it through the always-up tracker: a reborn worker
registers ``recover``, the tracker bumps the link generation, survivors
re-link (`/root/reference/tracker/dmlc_tracker/tracker.py:279-291`).

This module re-expresses that protocol for the JAX mesh, with a clean
split of planes:

* **control plane** — the rabit host collectives (brokered TCP via our
  tracker) already survive process death: the reborn process re-registers
  with ``recover`` and survivors re-link transparently inside
  ``RabitContext._with_recovery``.  Generation AGREEMENT therefore rides a
  rabit ``allreduce(max)``, which is exactly the piece of state that must
  outlive the broken data plane.
* **data plane** — generation ``g`` of the JAX mesh lives at coordinator
  address ``host:base_port+g``.  Re-initialization is a full teardown:
  ``jax.distributed.shutdown()`` + ``jax.extend.backend.clear_backends()``
  + ``initialize()`` at the new generation's port with the SAME
  process_id/world size.  (Donated/live device arrays die with the old
  backend — callers restore state from their checkpoint, the same
  contract as a reference worker reborn from ``LoadCheckPoint``.)

Protocol (:meth:`ElasticJaxMesh.resync`): every process proposes a
generation — survivors their current one, a reborn process (detected via
``DMLC_NUM_ATTEMPT`` > 0, or any process whose last collective raised)
current+1 — the rabit ``allreduce(max)`` agrees, and everyone at a lower
generation tears down and re-initializes.  Calling ``resync`` between
training phases is the sync-point pattern: cheap (one tiny host
allreduce), and a death anywhere surfaces at the next sync point instead
of wedging a device collective forever.

Proven end-to-end in
``tests/test_tracker_rabit.py::test_elastic_jax_mesh_rejoin_after_kill``:
rank 2 of 3 is killed mid-job, relaunched with a bumped attempt, and the
post-rejoin global-mesh reduction is bit-correct on every process.

**Checkpoint-free recovery** (:mod:`.reshard`): registering a
:class:`~.reshard.StateHandle` via :meth:`ElasticJaxMesh.register_state`
upgrades the rebuild from "teardown + callers reload from checkpoint" to
live redistribution — survivors snapshot their pytree shards to host
memory before teardown, the new cohort agrees a shard-ownership map over
the control plane, and missing shards move point-to-point to
reborn/remapped ranks, with leaf-granular checkpoint reads only for
shards no survivor holds.  ``resync()`` then returns the restored state
(:class:`ResyncResult`), not just "rebuilt".
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import numpy as np

from ..utils import check, get_env, log_info, log_warning
from ..utils.metrics import metrics
from ..utils.parameter import env_int, parse_lenient_bool
from . import reshard as _reshard
from .rabit import RabitContext

__all__ = ["ElasticJaxMesh", "ResyncResult"]

_BOUNDED_SHUTDOWN: Optional[bool] = None

# deliberately leaked coordination handles from torn-down generations on
# jaxes without a bounded shutdown barrier — see _teardown's clear_state
_ZOMBIE_HANDLES: list = []


def _reshard_enabled() -> bool:
    """``DMLC_RESHARD=0`` kill switch: fall back to the pre-reshard
    behavior (rebuild only; callers restore from checkpoint)."""
    v = parse_lenient_bool("DMLC_RESHARD")
    return True if v is None else v


def _data_plane_enabled() -> bool:
    """``DMLC_ELASTIC_DATA_PLANE=0`` runs the elastic protocol —
    generation agreement, ordered barriers, live resharding — WITHOUT
    ``jax.distributed`` teardown/init.  For cohorts whose collectives all
    ride the control plane (single-device CPU dev runs, jaxes without
    multi-process CPU support) the data-plane rebuild is pure overhead;
    everything else in the rejoin protocol is identical."""
    v = parse_lenient_bool("DMLC_ELASTIC_DATA_PLANE")
    return True if v is None else v


class ResyncResult:
    """Outcome of a sync point — truthy iff the mesh was rebuilt, so
    existing ``if mesh.resync():`` call sites keep working.  On a rebuild
    with a registered :class:`~.reshard.StateHandle`, ``state`` is the
    redistributed pytree (None when nothing was restored) and ``stats``
    the :class:`~.reshard.ReshardStats` for the round."""

    __slots__ = ("rebuilt", "generation", "state", "stats")

    def __init__(self, rebuilt: bool, generation: int,
                 state: Any = None, stats: Any = None) -> None:
        self.rebuilt = rebuilt
        self.generation = generation
        self.state = state
        self.stats = stats

    def __bool__(self) -> bool:
        return self.rebuilt

    def __repr__(self) -> str:
        return (f"ResyncResult(rebuilt={self.rebuilt}, "
                f"generation={self.generation}, "
                f"state={'<restored>' if self.state is not None else None}, "
                f"stats={self.stats})")


def _bounded_shutdown_supported() -> bool:
    """Whether this jax accepts heartbeat/shutdown budget kwargs on
    ``jax.distributed.initialize`` — the same vintages bound the shutdown
    barrier; older ones block it indefinitely and LOG(FATAL) on a dead
    peer."""
    global _BOUNDED_SHUTDOWN
    if _BOUNDED_SHUTDOWN is None:
        import inspect

        import jax
        try:
            params = inspect.signature(jax.distributed.initialize).parameters
            _BOUNDED_SHUTDOWN = "shutdown_timeout_seconds" in params
        except (TypeError, ValueError):    # C-level signature: assume new
            _BOUNDED_SHUTDOWN = True
    return _BOUNDED_SHUTDOWN


class ElasticJaxMesh:
    """Generation-addressed ``jax.distributed`` membership with rejoin.

    Parameters
    ----------
    ctx:        the process's :class:`RabitContext` (control plane).
    base_port:  coordinator port of generation 0; generation ``g`` binds
                ``base_port + g`` (a dead generation's socket may linger in
                TIME_WAIT, so each generation gets a fresh port).
    host:       coordinator host (process 0's address, default from
                ``DMLC_ELASTIC_HOST`` or 127.0.0.1).
    num_processes/process_id: mesh shape; default from the rabit context.
    """

    def __init__(self, ctx: RabitContext, base_port: int = 0,
                 host: str = "", num_processes: int = 0,
                 process_id: Optional[int] = None) -> None:
        self.ctx = ctx
        if not base_port:
            # the tpu launcher exports one base for the whole cohort so
            # every process derives identical generation addresses
            base_port = get_env("DMLC_ELASTIC_BASE_PORT", 0)
            check(base_port > 0, "ElasticJaxMesh needs base_port (or the "
                                 "launcher's DMLC_ELASTIC_BASE_PORT env)")
        self.base_port = int(base_port)
        self.host = host or get_env("DMLC_ELASTIC_HOST", "127.0.0.1")
        self.num_processes = num_processes or ctx.world_size
        self.process_id = ctx.rank if process_id is None else process_id
        self.generation = -1            # not initialized yet
        # a reborn process must drag the cohort forward: its previous
        # incarnation died inside some generation g, so it proposes g+1.
        # DMLC_NUM_ATTEMPT is the launcher's rebirth marker (every backend
        # sets it on retry) — the same signal that flips rabit to recover.
        self._dirty = get_env("DMLC_NUM_ATTEMPT", 0) > 0
        self._state_handle: Optional[_reshard.StateHandle] = None
        self._last_reshard: Tuple[Any, Any] = (None, None)

    def register_state(self, handle: "_reshard.StateHandle") -> None:
        """Register the live state to preserve across generation bumps.

        With a handle registered, ``ensure()`` snapshots
        ``handle.get_state()`` to host memory BEFORE tearing the data
        plane down and redistributes it across the new cohort afterwards
        (:func:`~.reshard.redistribute`), so :meth:`resync` returns the
        restored state instead of just "rebuilt".  COLLECTIVE: register
        at the same point relative to control-plane collectives on every
        rank — the redistribute rounds run inside ``ensure()`` cohort-wide
        (register on all ranks or none; ``DMLC_RESHARD=0`` disables
        uniformly via the env)."""
        self._state_handle = handle

    # -- data-plane lifecycle --------------------------------------------
    def _coordinator(self, gen: int) -> str:
        return f"{self.host}:{self.base_port + gen}"

    def _teardown(self, final: bool = False) -> None:
        import jax
        import jax.extend as jex

        def clear_state() -> None:
            # clear the client/service references so exit hooks / the
            # re-init don't trip over what a skipped or failed shutdown
            # left behind.  The old handles are stashed IMMORTAL, never
            # released: the client's C++ destructor issues a Disconnect,
            # which blocks on the shutdown barrier (dead peers never
            # arrive) and then LOG(FATAL)s the whole process — observed
            # live ~90s after dropping the last reference.  An extra
            # uncounted incref keeps the destructor from running even at
            # interpreter teardown.  jax._src is private and moves across
            # JAX releases: degrade to a warning rather than masking the
            # real failure above
            try:
                import ctypes

                from jax._src import distributed as _dist
                state = getattr(_dist, "global_state", None)
                for attr in ("preemption_sync_manager", "client", "service"):
                    obj = getattr(state, attr, None) if state else None
                    if obj is not None:
                        ctypes.pythonapi.Py_IncRef(ctypes.py_object(obj))
                        _ZOMBIE_HANDLES.append(obj)
                        setattr(state, attr, None)
            except Exception as e2:  # noqa: BLE001 — private-API drift
                log_warning("elastic: could not clear jax distributed "
                            "state (%s) — private API moved?", e2)

        if not _bounded_shutdown_supported():
            # this jax cannot bound the shutdown barrier: with a dead
            # peer in the cohort, shutdown() blocks on the barrier for
            # its full default budget and then LOG(FATAL)s the whole
            # process from C++ (client.h "Terminating process…").
            # Dropping the client references is the only survivable
            # teardown — the old generation's service dies with its
            # process or is garbage-collected with its last reference.
            log_warning("elastic: this jax has no bounded shutdown "
                        "barrier — dropping generation-%d client without "
                        "the barrier", self.generation)
            clear_state()
        else:
            try:
                jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001 — half-dead service
                log_warning("elastic: shutdown of generation %d raised "
                            "(%s) — proceeding", self.generation, e)
                clear_state()
        if not final:
            # the old backend holds client handles into the dead
            # coordination service; initialize() refuses to run while any
            # backend lives
            jex.backend.clear_backends()

    def _barrier(self, tag: str) -> None:
        """Control-plane rendezvous (cheap host allreduce; the rabit layer
        re-links around dead/reborn peers on its own).  A failed barrier
        means the teardown ordering it was pacing is NOT guaranteed —
        count it and mark the mesh dirty so the next sync point forces a
        generation bump instead of silently desyncing the cohort."""
        try:
            self.ctx.allreduce(np.array([0], np.int64), "max")
        except Exception as e:  # noqa: BLE001
            metrics.counter("elastic.barrier_failures").add(1)
            self._dirty = True
            log_warning("elastic: %s barrier failed (%s) — mesh marked "
                        "dirty, next sync point will bump", tag, e)

    def ensure(self, gen: int) -> None:
        """Make this process a member of mesh generation ``gen``.

        COLLECTIVE: every cohort member must call this with the same
        target generation (``resync`` guarantees it) — the teardown of
        the previous generation is ORDERED over the control plane.
        Follower clients must disconnect while the leader's coordination
        service still lives: a heartbeat or ShutdownTask RPC that lands
        on a torn-down service kills the whole process with an
        uncatchable C++ ``LOG(FATAL)`` (client.h "Terminating process…"),
        observed live when the leader rebuilt first.  The barriers are
        cohort-wide, so a reborn member (nothing to tear down) still
        paces the rendezvous and the rabit seq counters stay aligned.
        """
        check(gen >= 0, "generation must be >= 0")
        if gen == self.generation:
            return
        handle = self._state_handle
        reshard_on = handle is not None and _reshard_enabled()
        snap = None
        if reshard_on:
            # snapshot live shards to HOST memory before anything is torn
            # down: device arrays (donated or not) die with the backend,
            # host copies do not.  A failed snapshot degrades this rank to
            # a non-holder (peers/checkpoint cover it), never blocks the
            # rebuild.
            try:
                if getattr(handle, "snapshot", None) is not None:
                    # row-sharded owners (embed tables) hand back a ready
                    # HostSnapshot with ranged + replica blocks that the
                    # whole-leaf snapshot_tree path cannot express
                    snap = handle.snapshot()
                else:
                    state = handle.get_state()
                    if state is not None:
                        snap = _reshard.snapshot_tree(state)
            except Exception as e:  # noqa: BLE001 — degrade, don't wedge
                log_warning("elastic: state snapshot failed (%s) — this "
                            "rank recovers from peers/checkpoint", e)
                snap = None
        data_plane = _data_plane_enabled()
        if data_plane:
            import jax
            # without this, the coordination client's error-polling thread
            # LOG(FATAL)s the WHOLE process the moment any peer dies
            # ("client.h Terminating process because the JAX distributed
            # service detected fatal errors") — survivors must outlive a
            # peer death to rejoin.  the flag is version-dependent: degrade
            # to a warning on JAX builds that dropped/renamed it instead of
            # refusing to start
            try:
                jax.config.update("jax_enable_recoverability", True)
            except Exception as e:  # noqa: BLE001 — flag absent in this JAX
                log_warning("elastic: jax_enable_recoverability unavailable "
                            "(%s) — peer-death survival depends on this JAX "
                            "build's defaults", e)
        self._barrier("pre-rebuild")
        if self.process_id != 0:
            if self.generation >= 0 and data_plane:
                self._teardown()
            self._barrier("followers-down")
        else:
            self._barrier("followers-down")
            if self.generation >= 0 and data_plane:
                self._teardown()
        if self.generation < 0 and data_plane:
            # a process that COMPUTED before joining (a reborn rank redoes
            # its epoch from checkpoint first — see initialize()'s rebirth
            # caveat) has an initialized backend, and
            # jax.distributed.initialize refuses to run after any jax
            # call; clear it (live device arrays die — callers restore
            # from their host-side checkpoint, the documented contract)
            import jax.extend as jex
            jex.backend.clear_backends()
        log_info("elastic: joining mesh generation %d at %s "
                 "(process %d/%d%s)", gen, self._coordinator(gen),
                 self.process_id, self.num_processes,
                 "" if data_plane else ", control plane only")
        overlap = (reshard_on and data_plane and
                   parse_lenient_bool("DMLC_RESHARD_OVERLAP") is not False)
        reshard_box: dict = {}
        reshard_thread = None
        if overlap:
            # redistribute rides the rabit control plane ONLY (brokered
            # TCP through the tracker — never the jax backend), so its
            # fetch rounds can run concurrently with
            # jax.distributed.initialize and the coordination-service
            # rendezvous hides behind the bulk transfers.  The cohort is
            # already agreed (barriers above), so reborn/remapped ranks
            # participate exactly as in the sequential path.  Only this
            # thread touches ctx collectives until the join below.
            import threading

            def _run_redistribute() -> None:
                try:
                    reshard_box["out"] = _reshard.redistribute(
                        self.ctx, snap, plan=handle.plan,
                        checkpoint=handle.resolve_checkpoint(),
                        checkpoint_step=handle.checkpoint_step,
                        template=handle.resolve_template(),
                        generation=gen)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    reshard_box["err"] = e

            reshard_thread = threading.Thread(
                target=_run_redistribute, name="reshard-overlap",
                daemon=True)
            reshard_thread.start()
            metrics.counter("elastic.reshard_overlaps").add(1)
        if data_plane:
            # short heartbeat/shutdown budgets (env-tunable): a dead peer
            # must be detected in seconds, and teardown of a broken
            # generation must be BOUNDED — the default 300 s shutdown
            # timeout lets the gen-g service (process 0) and a surviving
            # client block each other long enough that the gen-g+1
            # rendezvous misses ITS window.  The next generation is a
            # fresh service on a fresh port; nothing of the old one is
            # worth waiting minutes for.
            kw = {}
            if _bounded_shutdown_supported():
                kw = dict(
                    heartbeat_timeout_seconds=env_int(
                        "DMLC_ELASTIC_HEARTBEAT_S", 10, minimum=1),
                    shutdown_timeout_seconds=env_int(
                        "DMLC_ELASTIC_SHUTDOWN_S", 10, minimum=1))
            # a jax that predates the budget kwargs still rebuilds the
            # mesh; its dead-peer detection is just slower and its teardown
            # goes through the barrier-less path in _teardown
            jax.distributed.initialize(
                coordinator_address=self._coordinator(gen),
                num_processes=self.num_processes,
                process_id=self.process_id, **kw)
        self.generation = gen
        self._dirty = False
        if reshard_on:
            if reshard_thread is not None:
                reshard_thread.join()
                if "err" in reshard_box:
                    raise reshard_box["err"]
                restored, stats = reshard_box["out"]
            else:
                # sequential path (DMLC_RESHARD_OVERLAP=0, or control
                # plane only): redistribute after the new generation is
                # up; peers → leaf-granular checkpoint → cohort-wide
                # error (see reshard.redistribute)
                restored, stats = _reshard.redistribute(
                    self.ctx, snap, plan=handle.plan,
                    checkpoint=handle.resolve_checkpoint(),
                    checkpoint_step=handle.checkpoint_step,
                    template=handle.resolve_template(), generation=gen)
            self._last_reshard = (restored, stats)
            if restored is not None and handle.set_state is not None:
                handle.set_state(restored)
        else:
            self._last_reshard = (None, None)

    # -- failure handling -------------------------------------------------
    def mark_failed(self) -> None:
        """Record that a data-plane collective failed (caller caught the
        exception); the next :meth:`resync` proposes a bump."""
        self._dirty = True

    def resync(self) -> "ResyncResult":
        """Sync point: agree on the cohort's generation over the control
        plane and re-initialize if it moved.  Returns a
        :class:`ResyncResult` — truthy iff the mesh was rebuilt (drop-in
        for the old bool).  With a :meth:`register_state` handle, a
        rebuild carries the redistributed state in ``.state`` (survivor
        shards reassembled over the control plane; checkpoint only for
        shards no survivor held), so callers re-place it with the new
        mesh's sharding instead of reloading from checkpoint.

        Two host ``allreduce(max)`` rounds — the rabit layer re-links
        around dead/reborn peers on its own (tracker ``recover``), so this
        works exactly when the data plane is broken:

        1. *learn*: max over every process's current generation — a reborn
           process arrives at generation -1 and must not guess the
           cohort's position;
        2. *agree*: dirty processes (reborn, or survivors whose last
           device collective raised) propose cohort+1, the rest cohort;
           the max wins and everyone below it rebuilds.
        """
        cohort = int(self.ctx.allreduce(
            np.array([self.generation], np.int64), "max")[0])
        propose = cohort + 1 if self._dirty else cohort
        agreed = int(self.ctx.allreduce(
            np.array([propose], np.int64), "max")[0])
        agreed = max(agreed, 0)   # first-ever sync point: start at gen 0
        if agreed == self.generation:
            return ResyncResult(False, self.generation)
        self.ensure(agreed)
        restored, stats = self._last_reshard
        return ResyncResult(True, self.generation, restored, stats)

    def initialize(self) -> None:
        """First join: generation 0, or — when reborn — whatever the
        surviving cohort agrees at the sync point.

        REBIRTH CAVEAT: on rebirth this resyncs immediately, which is
        only frame-aligned when the survivors' next control-plane
        collective is ALSO resync (they crashed past their last sync
        point).  If survivors run other collectives first (e.g. an
        epoch-loss allreduce before their resync, as
        ``examples/elastic_train.py`` does), a reborn process must SKIP
        initialize(), redo its work from the checkpoint, run the same
        collectives the survivors are blocked in, and let the shared
        sync point's :meth:`resync` perform the join — mixing resync's
        allreduce with a different collective at the same frame corrupts
        both."""
        if self._dirty:
            # don't guess the cohort's current generation; ask it
            self.resync()
        else:
            self.ensure(0)

    def close(self) -> None:
        """Graceful ORDERED cohort exit.

        Recoverable-task mode skips the coordination service's
        synchronized Shutdown barrier by design (the service says so in
        its log), so an unordered exit races: the leader (process 0, who
        HOSTS the service) can finish its own shutdown and exit while a
        follower's ShutdownTask RPC is in flight — and the follower side
        fails with an uncatchable C++ ``LOG(FATAL)`` (client.h
        "Terminating process…"), killing the process after all its work
        succeeded.  The control plane sequences the teardown instead:

        1. barrier: everyone has finished computing;
        2. followers disconnect (their ShutdownTask lands on a live
           service);
        3. barrier: followers confirm they are out;
        4. the leader tears down client + service last.
        """
        if self.generation < 0:
            return
        self._barrier("pre-close")
        data_plane = _data_plane_enabled()
        if self.process_id != 0:
            if data_plane:
                self._teardown(final=True)
            self._barrier("followers-out")
        else:
            self._barrier("followers-out")
            if data_plane:
                self._teardown(final=True)
        self.generation = -1
