"""Non-partitioned line reader for stdin / single files — capability parity
with reference ``src/io/single_file_split.h`` (own buffering + overflow logic
:91-156; selected for the ``stdin`` URI).
"""

from __future__ import annotations

import sys
from typing import Optional

from ..utils import DMLCError
from .filesys import open_stream
from .input_split import InputSplit

__all__ = ["SingleFileSplit"]


class SingleFileSplit(InputSplit):
    """Sequential line records from stdin or one file; no partitioning."""

    BUFFER_SIZE = 256 << 10  # reference uses 256KB (`single_file_split.h:91`)

    def __init__(self, uri: str):
        self.uri = uri
        self._stream = None
        self._open()

    def _open(self):
        if self._stream is not None and self._stream is not sys.stdin.buffer:
            self._stream.close()
        if self.uri in ("stdin://", "-", ""):
            self._stream = sys.stdin.buffer
        else:
            self._stream = open_stream(self.uri, "r")
        self._buf = b""
        self._pos = 0  # cursor into _buf; _buf is only rebuilt on refill
        self._eof = False

    @staticmethod
    def _find_nl(data: bytes, pos: int) -> int:
        ln = data.find(b"\n", pos)
        lr = data.find(b"\r", pos)
        if ln < 0:
            return lr
        if lr < 0:
            return ln
        return min(ln, lr)

    def next_record(self) -> Optional[bytes]:
        while True:
            # skip leading newline run
            n = len(self._buf)
            while self._pos < n and self._buf[self._pos] in (0x0A, 0x0D):
                self._pos += 1
            nl = self._find_nl(self._buf, self._pos)
            if nl >= 0:
                rec = self._buf[self._pos:nl]
                self._pos = nl + 1
                if rec:
                    return rec
                continue
            if self._eof:
                if self._pos < n:
                    rec = self._buf[self._pos:]
                    self._pos = n
                    return rec
                return None
            data = self._stream.read(self.BUFFER_SIZE)
            if not data:
                self._eof = True
            else:
                self._buf = self._buf[self._pos:] + data
                self._pos = 0

    def next_chunk(self) -> Optional[bytes]:
        recs = []
        total = 0
        while total < self.BUFFER_SIZE:
            r = self.next_record()
            if r is None:
                break
            recs.append(r)
            total += len(r) + 1
        if not recs:
            return None
        return b"\n".join(recs) + b"\n"

    def before_first(self) -> None:
        if self._stream is sys.stdin.buffer:
            raise DMLCError("cannot rewind stdin")
        self._open()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        if num_parts != 1:
            raise DMLCError("SingleFileSplit does not support partitioning")

    def close(self) -> None:
        if self._stream is not None and self._stream is not sys.stdin.buffer:
            self._stream.close()
