"""InputSplit wrappers: threaded prefetch, on-disk cache, epoch shuffle —
capability parity with reference ``threaded_input_split.h``,
``cached_input_split.h``, ``input_split_shuffle.h``.

Concurrency is added by *wrapping* (the reference's key architectural idea,
SURVEY §1): the interface never changes, a wrapper composes a
:class:`~dmlc_core_tpu.utils.ThreadedIter` producer around any split.
"""

from __future__ import annotations

import os
import random
import struct
from typing import List, Optional

from ..utils import DMLCError, ThreadedIter, check
from .input_split import InputSplit

__all__ = ["ThreadedInputSplit", "CachedInputSplit", "ShuffleInputSplit"]


class ThreadedInputSplit(InputSplit):
    """Chunk prefetch on a background thread (reference `threaded_input_split.h:23`,
    queue capacity 2 :33 — applied by default by ``create_input_split``)."""

    def __init__(self, base: InputSplit, max_capacity: int = 2):
        self.base = base
        self._iter: ThreadedIter[bytes] = ThreadedIter(max_capacity=max_capacity)
        self._iter.init(lambda _cell: base.next_chunk(), base.before_first)
        self._reset_record_iter()

    def extract_records(self, chunk, pos):
        return self.base.extract_records(chunk, pos)

    def next_chunk(self) -> Optional[bytes]:
        return self._iter.next()

    def next_record(self) -> Optional[bytes]:
        return self._next_record_via(self.next_chunk, self.base.extract_records)

    def before_first(self) -> None:
        self._iter.before_first()
        self._reset_record_iter()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        # quiesce the producer, repartition the base, restart
        self._iter.destroy()
        self.base.reset_partition(part_index, num_parts)
        self._iter = ThreadedIter(max_capacity=self._iter.max_capacity)
        self._iter.init(lambda _cell: self.base.next_chunk(), self.base.before_first)
        self._reset_record_iter()

    def hint_chunk_size(self, chunk_size: int) -> None:
        self.base.hint_chunk_size(chunk_size)

    def close(self) -> None:
        self._iter.destroy()
        self.base.close()


class CachedInputSplit(InputSplit):
    """First epoch streams chunks to a local cache file while serving them;
    later epochs replay the cache (reference `cached_input_split.h:148-189`).

    The cache is a simple length-prefixed chunk log.  Crash safety: the
    first pass writes ``<cache>.tmp.<pid>`` and atomically renames it into
    place before dropping the ``.done`` finalize marker, so a killed run
    leaves no half-written cache under the real name; framing is
    re-validated on open, so a truncated or corrupt survivor is discarded
    and rebuilt from the source instead of silently truncating the epoch.
    ``reset_partition`` is unsupported, as in the reference
    (`cached_input_split.h:87`).
    """

    def __init__(self, base: InputSplit, cache_file: str):
        self.base = base
        self.cache_file = cache_file
        self._tmp_file = f"{cache_file}.tmp.{os.getpid()}"
        self._cache_complete = (os.path.exists(cache_file + ".done")
                                and self._validate_cache())
        if not self._cache_complete:
            self._discard_cache()
        self._writer = None if self._cache_complete \
            else open(self._tmp_file, "wb")
        self._reader = None
        self._first_epoch = not self._cache_complete
        self._reset_record_iter()

    def _validate_cache(self) -> bool:
        """Walk the length-prefixed framing end to end; a short read or an
        out-of-bounds length means a damaged cache."""
        try:
            size = os.path.getsize(self.cache_file)
            with open(self.cache_file, "rb") as f:
                pos = 0
                while pos < size:
                    head = f.read(8)
                    if len(head) < 8:
                        return False
                    (n,) = struct.unpack("<Q", head)
                    pos += 8 + n
                    if pos > size:
                        return False
                    f.seek(n, 1)
            return True
        except OSError:
            return False

    def _discard_cache(self) -> None:
        # the marker goes first: if unlink dies between the two, a marker
        # without a cache file fails validation next open, not this order
        for path in (self.cache_file + ".done", self.cache_file):
            try:
                os.unlink(path)
            except OSError:
                pass

    def next_chunk(self) -> Optional[bytes]:
        if self._first_epoch:
            chunk = self.base.next_chunk()
            if chunk is None:
                self._finish_cache()
                return None
            self._writer.write(struct.pack("<Q", len(chunk)))
            self._writer.write(chunk)
            return chunk
        if self._reader is None:
            self._reader = open(self.cache_file, "rb")
        head = self._reader.read(8)
        if len(head) < 8:
            return None
        (n,) = struct.unpack("<Q", head)
        data = self._reader.read(n)
        if len(data) != n:
            raise DMLCError(f"corrupt input-split cache {self.cache_file}")
        return data

    def extract_records(self, chunk, pos):
        return self.base.extract_records(chunk, pos)

    def next_record(self) -> Optional[bytes]:
        return self._next_record_via(self.next_chunk, self.base.extract_records)

    def _finish_cache(self) -> None:
        if self._writer is not None:
            self._writer.flush()
            os.fsync(self._writer.fileno())
            self._writer.close()
            self._writer = None
            os.replace(self._tmp_file, self.cache_file)
            with open(self.cache_file + ".done", "w") as f:
                f.write("ok")
        self._cache_complete = True
        self._first_epoch = False

    def before_first(self) -> None:
        self._reset_record_iter()
        if self._first_epoch and not self._cache_complete:
            # restart an incomplete first pass from the source
            self.base.before_first()
            if self._writer is not None:
                self._writer.close()
            self._writer = open(self._tmp_file, "wb")
            return
        self._first_epoch = False
        if self._reader is not None:
            self._reader.close()
        self._reader = None

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise DMLCError("CachedInputSplit does not support ResetPartition "
                        "(reference cached_input_split.h:87)")

    def close(self) -> None:
        if self._writer is not None:
            # incomplete first pass: drop the partial tmp file — a future
            # open must rebuild from the source, not trust half a log
            self._writer.close()
            self._writer = None
            try:
                os.unlink(self._tmp_file)
            except OSError:
                pass
        if self._reader is not None:
            self._reader.close()
        self.base.close()


class ShuffleInputSplit(InputSplit):
    """Global shuffle by over-partitioning (reference `input_split_shuffle.h:18-137`).

    Each real partition is split into ``num_shuffle_parts`` sub-parts; every
    epoch visits the sub-parts in a seeded random order re-drawn per epoch
    (reference reshuffle in BeforeFirst `input_split_shuffle.h:23-32`).
    """

    def __init__(self, base: InputSplit, part_index: int, num_parts: int,
                 num_shuffle_parts: int = 16, seed: int = 0):
        check(num_shuffle_parts >= 1, "num_shuffle_parts must be >= 1")
        self.base = base
        self.part_index = part_index
        self.num_parts = num_parts
        self.num_shuffle_parts = num_shuffle_parts
        self._rng = random.Random(seed)
        self._order: List[int] = []
        self._order_pos = 0
        self._active = False
        self._reshuffle()

    def _sub_part(self, i: int) -> int:
        return self.part_index * self.num_shuffle_parts + i

    def _reshuffle(self) -> None:
        self._order = list(range(self.num_shuffle_parts))
        self._rng.shuffle(self._order)
        self._order_pos = 0
        self._active = False

    def _advance(self) -> bool:
        if self._order_pos >= len(self._order):
            return False
        sub = self._order[self._order_pos]
        self._order_pos += 1
        self.base.reset_partition(self._sub_part(sub),
                                  self.num_parts * self.num_shuffle_parts)
        self._active = True
        return True

    def next_record(self) -> Optional[bytes]:
        while True:
            if self._active:
                rec = self.base.next_record()
                if rec is not None:
                    return rec
                self._active = False
            if not self._advance():
                return None

    def next_chunk(self) -> Optional[bytes]:
        while True:
            if self._active:
                chunk = self.base.next_chunk()
                if chunk is not None:
                    return chunk
                self._active = False
            if not self._advance():
                return None

    def before_first(self) -> None:
        # a fresh permutation each epoch comes from advancing self._rng state
        self._reshuffle()

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        self.part_index, self.num_parts = part_index, num_parts
        self._reshuffle()

    def extract_records(self, chunk, pos):
        return self.base.extract_records(chunk, pos)

    def close(self) -> None:
        self.base.close()
