"""``ls``/``cat``/``cp``/``stat`` over any registered URI scheme.

Capability parity with the reference's standalone filesystem driver
(`test/filesys_test.cc`, documented as the ls/cat/cp CLI used for the S3
soak test in `test/README.md:1-30`) — but installed as a real subcommand
instead of a test binary::

    python -m dmlc_core_tpu.io.fscli ls  s3://bucket/dir
    python -m dmlc_core_tpu.io.fscli cat hdfs://nn:9870/data/part-0
    python -m dmlc_core_tpu.io.fscli cp  file:///tmp/in s3://bucket/out
    python -m dmlc_core_tpu.io.fscli stat https://host/file.bin

``cp`` streams in bounded chunks (never materializes the file), so it
exercises exactly the ranged-read/multipart-write paths the ingest pipeline
uses.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from ..utils import DMLCError
from .filesys import get_filesystem, open_seek_stream_for_read, open_stream
from .uri import URI

__all__ = ["main"]

_CHUNK = 1 << 20


def cmd_ls(uri_str: str) -> int:
    u = URI(uri_str)
    fs = get_filesystem(u)
    for info in fs.list_directory(u):
        kind = "d" if info.type == "dir" else "-"
        print(f"{kind} {info.size:>14d}  {info.path}")
    return 0


def cmd_stat(uri_str: str) -> int:
    fs = get_filesystem(URI(uri_str))
    info = fs.get_path_info(URI(uri_str))
    print(f"{info.type} {info.size} {info.path}")
    return 0


def cmd_cat(uri_str: str) -> int:
    with open_seek_stream_for_read(uri_str) as src:
        while True:
            chunk = src.read(_CHUNK)
            if not chunk:
                # flush HERE so a closed pipe raises inside main's handler,
                # not at interpreter-shutdown where it prints noise
                sys.stdout.buffer.flush()
                return 0
            sys.stdout.buffer.write(chunk)


def cmd_cp(src_uri: str, dst_uri: str) -> int:
    copied = 0
    with open_seek_stream_for_read(src_uri) as src, \
            open_stream(dst_uri, "w") as dst:
        while True:
            chunk = src.read(_CHUNK)
            if not chunk:
                break
            dst.write(chunk)
            copied += len(chunk)
    print(f"copied {copied} bytes {src_uri} -> {dst_uri}", file=sys.stderr)
    return 0


def cmd_pack(src_uri: str, dst_uri: str) -> int:
    """Each text line (newline stripped) becomes one recordio record —
    the im2rec-style list→.rec conversion, format-agnostic."""
    from .recordio import RecordIOWriter
    n = 0
    with open_seek_stream_for_read(src_uri) as src, \
            open_stream(dst_uri, "w") as dst:
        w = RecordIOWriter(dst)
        carry = b""
        while True:
            chunk = src.read(_CHUNK)
            if not chunk:
                break
            carry += chunk
            *lines, carry = carry.split(b"\n")
            for line in lines:
                w.write_record(line)
                n += 1
        if carry:
            w.write_record(carry)
            n += 1
    print(f"packed {n} records {src_uri} -> {dst_uri}", file=sys.stderr)
    return 0


def cmd_unpack(src_uri: str, dst_uri: str) -> int:
    """Inverse of pack: one text line per record."""
    from .recordio import RecordIOReader
    n = 0
    with open_seek_stream_for_read(src_uri) as src, \
            open_stream(dst_uri, "w") as dst:
        r = RecordIOReader(src)
        while True:
            rec = r.next_record()
            if rec is None:
                break
            dst.write(rec)
            dst.write(b"\n")
            n += 1
    print(f"unpacked {n} records {src_uri} -> {dst_uri}", file=sys.stderr)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dmlc-fs",
        description="ls/cat/cp/stat over any URI scheme "
                    "(file, http(s), s3, gs, hdfs, azure); pack/unpack "
                    "convert line-text <-> recordio")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("ls").add_argument("uri")
    sub.add_parser("stat").add_argument("uri")
    sub.add_parser("cat").add_argument("uri")
    for name in ("cp", "pack", "unpack"):
        sp = sub.add_parser(name)
        sp.add_argument("src")
        sp.add_argument("dst")
    args = p.parse_args(argv)
    try:
        if args.cmd == "ls":
            return cmd_ls(args.uri)
        if args.cmd == "stat":
            return cmd_stat(args.uri)
        if args.cmd == "cat":
            return cmd_cat(args.uri)
        if args.cmd == "pack":
            return cmd_pack(args.src, args.dst)
        if args.cmd == "unpack":
            return cmd_unpack(args.src, args.dst)
        return cmd_cp(args.src, args.dst)
    except DMLCError as e:
        print(f"dmlc-fs: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # `dmlc-fs cat big | head`: downstream closed — exit quietly,
        # pointing stdout at devnull so interpreter shutdown can't re-raise
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
