"""RecordIO codec — capability parity with reference ``include/dmlc/recordio.h``
+ ``src/recordio.cc``.

Wire format (reference `recordio.h:16-45`): each record is framed as::

    [u32 kMagic][u32 lrec] payload [zero-pad to 4-byte alignment]

where ``lrec = cflag << 29 | length`` (``EncodeLRec`` `recordio.h:52`) and
``kMagic = 0xced7230a`` (`recordio.h:45`).  The format is *splittable*: a
reader dropped at an arbitrary 4-aligned offset can scan forward for the magic
word to find a frame start.  That only works because the **writer escapes
payload magic collisions** (`src/recordio.cc:11-51`): any 4-aligned occurrence
of the magic word inside the payload splits the record into multi-part frames
(cflag 1=start, 2=middle, 3=end; the removed magic word is re-inserted between
parts on read), so written frame *content* never contains an aligned magic
word.  ``lrec`` cannot collide either since cflag ≤ 3 keeps it < 2^31 while
the magic's top bits are 0b110.

TPU-native expression: the aligned magic scan and escape-split are vectorized
with numpy (the C++ native module accelerates them further); the frame layout
is byte-identical to the reference so ``.rec`` datasets interoperate.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, List, Optional, Tuple

import numpy as np

from ..utils import DMLCError, check, check_lt

__all__ = [
    "KMAGIC", "encode_lrec", "decode_lrec",
    "RecordIOWriter", "RecordIOReader", "RecordIOChunkReader",
]

KMAGIC = 0xCED7230A
_MAGIC_BYTES = struct.pack("<I", KMAGIC)
_MAX_LEN = (1 << 29) - 1


def encode_lrec(cflag: int, length: int) -> int:
    """Reference ``EncodeLRec`` (`recordio.h:52`)."""
    check_lt(length, 1 << 29, "recordio record too long")
    return (cflag << 29) | length


def decode_lrec(lrec: int) -> Tuple[int, int]:
    """Return (cflag, length) (reference ``DecodeFlag``/``DecodeLength`` `recordio.h:58-66`)."""
    return lrec >> 29, lrec & _MAX_LEN


def _aligned_magic_positions(data: bytes) -> np.ndarray:
    """4-aligned offsets where the magic word occurs inside ``data``."""
    lower = len(data) & ~3
    if lower == 0:
        return np.empty(0, dtype=np.int64)
    words = np.frombuffer(data, dtype="<u4", count=lower // 4)
    return (np.nonzero(words == KMAGIC)[0] * 4).astype(np.int64)


class RecordIOWriter:
    """Frame writer with magic escaping (reference `recordio.h:38`, `src/recordio.cc:11-51`)."""

    def __init__(self, stream: BinaryIO):
        self.stream = stream
        self.except_counter = 0  # count of escaped magic collisions (`recordio.h:85`)

    def write_record(self, data: bytes) -> None:
        check_lt(len(data), 1 << 29, "recordio record too long")
        positions = _aligned_magic_positions(data)
        dptr = 0
        parts: List[bytes] = []
        for i in map(int, positions):
            cflag = 1 if dptr == 0 else 2
            parts.append(_MAGIC_BYTES)
            parts.append(struct.pack("<I", encode_lrec(cflag, i - dptr)))
            parts.append(data[dptr:i])
            dptr = i + 4
            self.except_counter += 1
        cflag = 3 if dptr != 0 else 0
        parts.append(_MAGIC_BYTES)
        parts.append(struct.pack("<I", encode_lrec(cflag, len(data) - dptr)))
        parts.append(data[dptr:])
        pad = (-(len(data) - dptr)) & 3
        if pad:
            parts.append(b"\x00" * pad)
        self.stream.write(b"".join(parts))


def _read_frame(read_exact) -> Optional[Tuple[int, bytes]]:
    """Read one frame: returns (cflag, content) or None at EOF."""
    head = read_exact(4, allow_eof=True)
    if head is None:
        return None
    if head != _MAGIC_BYTES:
        raise DMLCError(
            f"recordio: bad magic {head!r} (corrupt stream or unaligned read)")
    lrec = struct.unpack("<I", read_exact(4))[0]
    cflag, length = decode_lrec(lrec)
    upper = (length + 3) & ~3
    buf = read_exact(upper)
    return cflag, buf[:length]


class RecordIOReader:
    """Sequential reader rejoining multi-part records
    (reference ``RecordIOReader::NextRecord`` `src/recordio.cc:53+`)."""

    def __init__(self, stream: BinaryIO):
        self.stream = stream

    def _read_exact(self, n: int, allow_eof: bool = False) -> Optional[bytes]:
        b = self.stream.read(n)
        if not b and allow_eof:
            return None
        if len(b) != n:
            raise DMLCError(f"recordio: truncated stream (wanted {n}, got {len(b)})")
        return b

    def next_record(self) -> Optional[bytes]:
        frame = _read_frame(self._read_exact)
        if frame is None:
            return None
        cflag, content = frame
        if cflag == 0:
            return content
        if cflag != 1:
            raise DMLCError(f"recordio: unexpected continuation frame (cflag={cflag})")
        # multi-part record: rejoin with the escaped magic re-inserted
        parts = [content]
        while True:
            frame = _read_frame(self._read_exact)
            if frame is None:
                raise DMLCError("recordio: EOF inside multi-part record")
            cflag, content = frame
            if cflag not in (2, 3):
                raise DMLCError(f"recordio: bad multi-part cflag {cflag}")
            parts.append(_MAGIC_BYTES)
            parts.append(content)
            if cflag == 3:
                return b"".join(parts)

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


class RecordIOChunkReader:
    """Parse records out of an in-memory blob of whole frames, optionally only
    a [part_index/num_parts] sub-range split at frame boundaries
    (reference ``RecordIOChunkReader`` `recordio.h:166-187`).

    The blob must start at a frame boundary (as produced by the recordio
    InputSplit).  Sub-range boundaries are found by scanning for aligned magic
    words with cflag ∈ {0, 1} — valid because written content never contains
    aligned magic.
    """

    def __init__(self, blob: bytes, part_index: int = 0, num_parts: int = 1):
        check(num_parts >= 1, "num_parts must be >= 1")
        if num_parts == 1:
            begin, end = 0, len(blob)
        else:
            nstep = (len(blob) + num_parts - 1) // num_parts
            pbegin = min(nstep * part_index, len(blob))
            pend = min(nstep * (part_index + 1), len(blob))
            begin = _seek_record_boundary(blob, pbegin)
            end = _seek_record_boundary(blob, pend)
        self._view = memoryview(blob)[begin:end]
        self._pos = 0

    def _read_exact(self, n: int, allow_eof: bool = False) -> Optional[bytes]:
        if self._pos >= len(self._view) and allow_eof:
            return None
        if self._pos + n > len(self._view):
            raise DMLCError("recordio chunk: truncated frame")
        out = bytes(self._view[self._pos:self._pos + n])
        self._pos += n
        return out

    def next_record(self) -> Optional[bytes]:
        return RecordIOReader.next_record(self)  # type: ignore[arg-type]

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec


def _seek_record_boundary(blob: bytes, pos: int) -> int:
    """First offset >= pos (4-aligned) holding a frame header with cflag∈{0,1}
    (the scan the reference runs in `src/io/recordio_split.cc:9-42`)."""
    pos = (pos + 3) & ~3
    n = len(blob)
    while pos + 8 <= n:
        if blob[pos:pos + 4] == _MAGIC_BYTES:
            lrec = struct.unpack("<I", blob[pos + 4:pos + 8])[0]
            cflag, _ = decode_lrec(lrec)
            if cflag in (0, 1):
                return pos
        pos += 4
    return n
