"""Partition-correct record splitting — capability parity with reference
``src/io/input_split_base.{h,cc}``, ``line_split.{h,cc}``,
``recordio_split.{h,cc}``.

Core invariant (reference ``ResetPartition`` `input_split_base.cc:30-64`):
given N partitions over the concatenated byte space of all matched files, the
provisional byte ranges ``[k*step, (k+1)*step)`` are *realigned* so both ends
land on record-begin boundaries, using the same boundary-seek function for
begin and end.  Hence partition k's range is
``[seek(k*step), seek((k+1)*step))`` — the union over k covers every record
exactly once, with no record split or duplicated (off-by-one here is silent
data loss; property-tested in tests/test_input_split.py).

Boundary rules:

* a file start is always a record begin (records never span files);
* line records: the next record begins after the next ``\\n``
  (`line_split.cc:9-26`); a record beginning exactly at the probe offset
  belongs to the *previous* partition (consistent on both ends);
* recordio records: the next record begins at the next 4-aligned magic word
  whose frame cflag ∈ {0, 1} (`recordio_split.cc:9-42`) — a frame starting
  exactly at the probe offset starts *this* partition (again consistent).

Chunk reads return blobs containing only whole records, found by scanning the
tail for the last record begin and carrying the remainder as overflow
(`input_split_base.cc:211-239`); since both partition ends are record
boundaries, the partition byte range itself contains exactly whole records.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils import DMLCError, check
from .filesys import (FileInfo, FileSystem, get_filesystem,
                      list_directory_recursive)
from .recordio import KMAGIC, _MAGIC_BYTES, decode_lrec
from .uri import URI

__all__ = ["InputSplit", "InputSplitBase", "LineSplitter", "RecordIOSplitter",
           "expand_uris"]

_NEWLINE = (0x0A, 0x0D)  # \n \r


def expand_uris(uri: str, fs_hint: Optional[FileSystem] = None) -> List[FileInfo]:
    """Expand ``;``-separated paths, ``*``/``?`` wildcards and directories
    (recursively) into a flat file list
    (reference ``ConvertToURIs``/``InitInputFileInfo`` `input_split_base.cc:96-175`).
    Zero-size files are skipped (they hold no records)."""
    out: List[FileInfo] = []
    for piece in uri.split(";"):
        if not piece:
            continue
        u = URI(piece)
        fs = fs_hint or get_filesystem(u)
        if ("*" in piece or "?" in piece) and hasattr(fs, "glob"):
            paths = fs.glob(u.name if u.protocol else piece)
            if not paths:
                raise DMLCError(f"InputSplit: pattern {piece!r} matched no files")
            for p in paths:
                info = fs.get_path_info(URI(p))
                if info.type == "dir":
                    out.extend(list_directory_recursive(fs, URI(p)))
                else:
                    out.append(info)
        else:
            info = fs.get_path_info(u)
            if info.type == "dir":
                out.extend(list_directory_recursive(fs, u))
            else:
                out.append(info)
    files = [f for f in out if f.size > 0]
    if not files:
        raise DMLCError(f"InputSplit: no non-empty files matched {uri!r}")
    return files


class InputSplit:
    """Abstract record-stream interface (reference ``InputSplit`` `io.h:135-281`).

    ``extract_records`` is part of the contract: it is the record grammar that
    lets wrappers (threaded/cached) iterate single records out of the whole-
    record chunks any split produces.  Wrappers delegate it to their base.
    """

    def next_record(self) -> Optional[bytes]:
        raise NotImplementedError

    def next_chunk(self) -> Optional[bytes]:
        raise NotImplementedError

    def extract_records(self, chunk: bytes, pos: int) -> Tuple[Optional[bytes], int]:
        """Extract one record starting at pos; return (record, new_pos) or
        (None, pos) at chunk end."""
        raise NotImplementedError

    # -- shared chunk→record iteration state used by base + wrappers --
    def _reset_record_iter(self) -> None:
        self._ri_chunk: Optional[bytes] = None
        self._ri_pos = 0

    def _next_record_via(self, next_chunk_fn, extractor) -> Optional[bytes]:
        if not hasattr(self, "_ri_pos"):
            self._reset_record_iter()
        while True:
            if self._ri_chunk is not None:
                rec, new_pos = extractor(self._ri_chunk, self._ri_pos)
                if rec is not None:
                    self._ri_pos = new_pos
                    return rec
            chunk = next_chunk_fn()
            if chunk is None:
                return None
            if isinstance(chunk, memoryview):
                # record extractors use bytes scans; the chunk-level
                # consumers (parsers) stay zero-copy
                chunk = bytes(chunk)
            self._ri_chunk = chunk
            self._ri_pos = 0

    def before_first(self) -> None:
        raise NotImplementedError

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        raise NotImplementedError

    def hint_chunk_size(self, chunk_size: int) -> None:
        pass

    def __iter__(self):
        while True:
            rec = self.next_record()
            if rec is None:
                return
            yield rec

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class InputSplitBase(InputSplit):
    """Multi-file byte-range partitioning engine (reference `input_split_base.cc`)."""

    KBUFFER_SIZE = 2 << 20  # 2MiB default chunk (reference `input_split_base.h:40`)
    align_bytes = 1

    def __init__(self, uri: str, part_index: int, num_parts: int):
        self.uri = uri
        self.files = expand_uris(uri)
        sizes = np.array([f.size for f in self.files], dtype=np.int64)
        # cumulative start offset of each file in the global byte space
        # (reference `Init` `input_split_base.cc:13-28`)
        self.file_offset = np.concatenate([[0], np.cumsum(sizes)])
        self.total_size = int(self.file_offset[-1])
        self.chunk_size = self.KBUFFER_SIZE
        self._fs = get_filesystem(URI(self.files[0].path))
        self._open_file_index: Optional[int] = None
        self._open_stream = None
        # local files are mmapped: chunks become zero-copy memoryviews with
        # no overflow-carry concatenation (the reference's C++ path copies
        # into a Chunk buffer, `input_split_base.cc:241-279`; a mapped file
        # needs neither the copy nor the carry — the cursor just advances to
        # the last record begin).  VERDICT r1 #2.
        from .filesys import LocalFileSystem
        self._mmaps: dict = {}
        self._use_mmap = (isinstance(self._fs, LocalFileSystem)
                          and all(f.path not in ("-", "") for f in self.files))
        self.reset_partition(part_index, num_parts)

    # ---- virtual boundary functions ----
    def seek_record_begin(self, data: bytes, from_pos: int) -> Optional[int]:
        """Offset (within data, >= from_pos) of the next record begin assuming
        ``data[from_pos]`` may be mid-record; None if not found in data."""
        raise NotImplementedError

    def find_last_record_begin(self, data: bytes) -> int:
        """Offset of the last record begin in data (0 if only one record begins
        at 0; data[0] is guaranteed to be a record begin)."""
        raise NotImplementedError

    # ---- partitioning ----
    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(0 <= part_index < num_parts,
              f"bad partition {part_index}/{num_parts}")
        nstep = (self.total_size + num_parts - 1) // num_parts
        a = self.align_bytes
        pbegin = min(nstep * part_index // a * a, self.total_size)
        pend = min(nstep * (part_index + 1) // a * a, self.total_size)
        self.begin = self._adjust_to_record_begin(pbegin)
        self.end = self._adjust_to_record_begin(pend)
        self.part_index, self.num_parts = part_index, num_parts
        self.before_first()

    def _adjust_to_record_begin(self, pos: int) -> int:
        """Realign a provisional offset to the next record-begin boundary
        (reference `input_split_base.cc:30-64` via SeekRecordBegin)."""
        if pos <= 0:
            return 0
        if pos >= self.total_size:
            return self.total_size
        # file starts are record begins
        fidx = int(np.searchsorted(self.file_offset, pos, side="right")) - 1
        if self.file_offset[fidx] == pos:
            return pos
        file_end = int(self.file_offset[fidx + 1])
        # scan forward within this file only (records never span files)
        scan_pos = pos
        step = 64 << 10
        carry = b""
        carry_base = pos
        while scan_pos < file_end:
            data = carry + self._pread(scan_pos, min(step, file_end - scan_pos))
            found = self.seek_record_begin(data, 0)
            if found is not None:
                return carry_base + found
            # keep a small tail so multi-byte boundaries spanning the block
            # edge are found (recordio header = 8 bytes)
            keep = min(len(data), 8)
            carry = data[len(data) - keep:]
            scan_pos += min(step, file_end - scan_pos)
            carry_base = scan_pos - keep
        return file_end

    # ---- raw cross-file reads ----
    def _mmap_for(self, fidx: int):
        mm = self._mmaps.get(fidx)
        if mm is None:
            import mmap as _mmap
            with open(self.files[fidx].path, "rb") as f:
                mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            try:
                mm.madvise(_mmap.MADV_SEQUENTIAL)
            except (AttributeError, OSError):
                pass
            self._mmaps[fidx] = mm
        return mm

    def _pread(self, offset: int, size: int) -> bytes:
        """Read ``size`` bytes at global ``offset``, crossing file boundaries
        (reference ``Read`` `input_split_base.cc:177-209`)."""
        segs = []
        remaining = size
        while remaining > 0 and offset < self.total_size:
            fidx = int(np.searchsorted(self.file_offset, offset, side="right")) - 1
            in_file = offset - int(self.file_offset[fidx])
            n = min(remaining, int(self.file_offset[fidx + 1]) - offset)
            if self._use_mmap:
                mm = self._mmap_for(fidx)
                data = mm[in_file:in_file + n]
            else:
                stream = self._stream_for(fidx)
                stream.seek(in_file)
                data = stream.read(n)
            if len(data) != n:
                raise DMLCError(
                    f"short read from {self.files[fidx].path}: wanted {n}, got {len(data)}")
            segs.append(data)
            offset += n
            remaining -= n
        # single-segment reads (the common case) return without re-copying
        return segs[0] if len(segs) == 1 else b"".join(segs)

    def _stream_for(self, fidx: int):
        if self._open_file_index != fidx:
            if self._open_stream is not None:
                self._open_stream.close()
            self._open_stream = self._fs.open_for_read(URI(self.files[fidx].path))
            self._open_file_index = fidx
        return self._open_stream

    # ---- chunked whole-record reads ----
    def before_first(self) -> None:
        self._cur = self.begin
        self._overflow = b""
        self._reset_record_iter()

    def hint_chunk_size(self, chunk_size: int) -> None:
        self.chunk_size = max(chunk_size, 1 << 10)

    def next_chunk(self) -> Optional[bytes]:
        """Next blob of whole records (reference ``NextChunkEx``/``ReadChunk``
        `input_split_base.cc:211-258`).  Local (mmapped) sources return
        zero-copy memoryviews; remote sources use the overflow-carry scheme."""
        if self._use_mmap:
            return self._next_chunk_mmap()
        while True:
            if self._cur >= self.end and not self._overflow:
                return None
            want = min(self.chunk_size, self.end - self._cur)
            data = self._overflow + self._pread(self._cur, want)
            self._cur += want
            if self._cur >= self.end:
                # partition range holds exactly whole records: flush all
                self._overflow = b""
                return data if data else None
            cut = self.find_last_record_begin(data)
            if cut == 0:
                # no record boundary inside the buffer: grow and retry
                # (reference Chunk doubling growth `input_split_base.cc:241-279`)
                self._overflow = data
                self.chunk_size *= 2
                continue
            self._overflow = data[cut:]
            return data[:cut]

    def _next_chunk_mmap(self) -> Optional[memoryview]:
        """Zero-copy chunking: advance the cursor to the last record begin
        inside the window instead of carrying an overflow tail.  Chunks never
        span files (records never do, and file starts are record begins)."""
        while True:
            if self._cur >= self.end:
                return None
            fidx = int(np.searchsorted(self.file_offset, self._cur,
                                       side="right")) - 1
            foff = int(self.file_offset[fidx])
            file_end = min(self.end, int(self.file_offset[fidx + 1]))
            want = min(self.chunk_size, file_end - self._cur)
            local = self._cur - foff
            mm = self._mmap_for(fidx)
            if self._cur + want >= file_end:
                # partition/file end is a record boundary: take it all
                cut = want
            else:
                cut = self._find_cut_mm(mm, local, local + want)
                if cut <= 0:
                    # no record boundary inside the window: grow and retry
                    self.chunk_size *= 2
                    continue
            self._cur += cut
            return memoryview(mm)[local:local + cut]

    def _find_cut_mm(self, mm, start: int, end: int) -> int:
        """Length from ``start`` to the last record begin in ``mm[start:end)``
        (0 = none).  Default routes through :meth:`find_last_record_begin` on
        a zero-copy view; splitters with bytes-only scans override."""
        return self.find_last_record_begin(memoryview(mm)[start:end])

    def next_record(self) -> Optional[bytes]:
        """Iterate single records over chunks (reference ``NextRecord`` path)."""
        return self._next_record_via(self.next_chunk, self.extract_records)

    def close(self) -> None:
        if self._open_stream is not None:
            self._open_stream.close()
            self._open_stream = None
            self._open_file_index = None
        for mm in self._mmaps.values():
            try:
                mm.close()
            except (BufferError, OSError):
                pass  # live memoryviews pin the map; dropped with the object
        self._mmaps = {}


class LineSplitter(InputSplitBase):
    """Records are text lines (reference `line_split.{h,cc}`).

    A record is a maximal run of non-newline bytes; ``\\r``/``\\n`` runs
    separate records (so ``\\r\\n`` yields one boundary and empty lines produce
    no records, matching the reference's extract semantics
    `line_split.cc:36-55`).
    """

    align_bytes = 1

    @staticmethod
    def _find_newline(data: bytes, pos: int) -> int:
        """Offset of the first \\n or \\r at/after pos, or -1."""
        ln = data.find(b"\n", pos)
        lr = data.find(b"\r", pos)
        if ln < 0:
            return lr
        if lr < 0:
            return ln
        return min(ln, lr)

    def seek_record_begin(self, data: bytes, from_pos: int) -> Optional[int]:
        # consume to the first newline, then skip the newline run
        i = self._find_newline(data, from_pos)
        if i < 0:
            return None
        n = len(data)
        while i < n and data[i] in _NEWLINE:
            i += 1
        return i if i < n else None

    def find_last_record_begin(self, data: bytes) -> int:
        cut = max(data.rfind(b"\n"), data.rfind(b"\r"))
        return cut + 1 if cut >= 0 else 0

    def _find_cut_mm(self, mm, start: int, end: int) -> int:
        # mmap.rfind scans the mapped pages directly — no slice copy
        cut = max(mm.rfind(b"\n", start, end), mm.rfind(b"\r", start, end))
        return cut + 1 - start if cut >= 0 else 0

    def extract_records(self, chunk: bytes, pos: int) -> Tuple[Optional[bytes], int]:
        n = len(chunk)
        # skip leading newline run
        while pos < n and chunk[pos] in _NEWLINE:
            pos += 1
        if pos >= n:
            return None, pos
        end = self._find_newline(chunk, pos)
        if end < 0:
            end = n
        return chunk[pos:end], end


class RecordIOSplitter(InputSplitBase):
    """Records are recordio frames (reference `recordio_split.{h,cc}`).

    ``next_record`` returns the *payload* with multi-part records rejoined
    (reference `recordio_split.cc:44-82`); ``next_chunk`` returns raw frame
    blobs suitable for :class:`~dmlc_core_tpu.io.recordio.RecordIOChunkReader`.
    """

    align_bytes = 4

    def seek_record_begin(self, data: bytes, from_pos: int) -> Optional[int]:
        pos = (from_pos + 3) & ~3
        n = len(data)
        while pos + 8 <= n:
            if data[pos:pos + 4] == _MAGIC_BYTES:
                cflag, _ = decode_lrec(
                    int.from_bytes(data[pos + 4:pos + 8], "little"))
                if cflag in (0, 1):
                    return pos
            pos += 4
        return None

    def find_last_record_begin(self, data: bytes) -> int:
        lower = len(data) & ~3
        if lower < 8:
            return 0
        words = np.frombuffer(data, dtype="<u4", count=lower // 4)
        magic_at = np.nonzero(words[:-1] == KMAGIC)[0]
        for w in reversed(magic_at):
            cflag = int(words[w + 1]) >> 29
            if cflag in (0, 1):
                return int(w) * 4
        return 0

    def extract_records(self, chunk: bytes, pos: int) -> Tuple[Optional[bytes], int]:
        n = len(chunk)
        if pos + 8 > n:
            return None, pos
        parts: List[bytes] = []
        while True:
            if chunk[pos:pos + 4] != _MAGIC_BYTES:
                raise DMLCError("recordio split: lost frame alignment")
            cflag, length = decode_lrec(
                int.from_bytes(chunk[pos + 4:pos + 8], "little"))
            upper = (length + 3) & ~3
            if pos + 8 + upper > n:
                raise DMLCError("recordio split: truncated frame in chunk")
            content = chunk[pos + 8:pos + 8 + length]
            pos += 8 + upper
            if cflag == 0:
                return content, pos
            if cflag == 1:
                parts = [content]
            elif cflag in (2, 3):
                parts.append(_MAGIC_BYTES)
                parts.append(content)
                if cflag == 3:
                    return b"".join(parts), pos
            else:
                raise DMLCError(f"recordio split: bad cflag {cflag}")
