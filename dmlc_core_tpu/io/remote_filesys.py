"""Remote object-store filesystems: HTTP, S3, GCS, WebHDFS, Azure.

Capability parity with the reference's biggest native piece,
``src/io/s3_filesys.{h,cc}`` (1012 LoC) plus ``hdfs_filesys.cc`` and
``azure_filesys.cc``:

* :class:`RangedReadStream` — the ``CURLReadStreamBase`` equivalent
  (`s3_filesys.cc:219-361`): a seekable read stream over HTTP ranged GETs
  with buffered fill and **restart-on-seek** (`s3_filesys.cc:234-239` —
  a seek outside the buffer drops the in-flight transfer and re-issues a
  Range request at the new offset).
* :class:`S3FileSystem` — AWS **SigV4** request signing (the reference used
  v2 HMAC-SHA1, `s3_filesys.cc:90-121`; v4 is what current S3 requires),
  ``ListObjectsV2`` XML parsing (`s3_filesys.cc:801`), and **multipart
  upload** write streams (Initiate/UploadPart/Complete,
  `s3_filesys.cc:747-799`) with the same ≥5MB part buffering
  (`s3_filesys.cc:646-653`). Credentials from the environment incl. session
  token, region and custom endpoint (`s3_filesys.cc:926` ctor).
* :class:`GCSFileSystem` — ``gs://`` through the S3-compatible XML API
  (HMAC interop keys), the TPU-idiomatic object store playing S3's role.
* :class:`WebHDFSFileSystem` — ``hdfs://`` over the WebHDFS REST API
  (the reference wraps libhdfs JNI, `hdfs_filesys.cc:31-75`; REST keeps the
  same Open/Read-at-offset/GetPathInfo/List surface with zero native deps).
* :class:`AzureFileSystem` — ``azure://`` blob listing (the reference's
  Azure backend is listing-only as well, `azure_filesys.cc:42-80`).

Everything speaks plain ``http.client``, so the full wire behavior is unit-
testable against in-process fake servers (tests/test_remote_filesys.py) —
the moral equivalent of the reference's S3 soak test (`test/README.md:1-30`)
without needing cloud credentials or egress.
"""

from __future__ import annotations

import datetime as _dt
import email.utils
import hashlib
import hmac
import http.client
import io
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import BinaryIO, Dict, List, Optional, Tuple

from ..utils.parameter import env_int, get_env
from ..utils import (Deadline, DeadlineExpired, DMLCError, RetriesExhausted,
                     RetryPolicy, check, fault_point, get_env)
from .filesys import FS_REGISTRY, FileInfo, FileSystem
from .uri import URI

__all__ = [
    "RangedReadStream", "HttpFileSystem", "S3FileSystem", "GCSFileSystem",
    "WebHDFSFileSystem", "AzureFileSystem", "sign_v4",
]

_DEFAULT_BUFFER = 2 << 20      # fill granularity (ref kBufferSize 2MiB, input_split_base.h:40)
_MIN_PART_SIZE = 5 << 20       # S3 minimum multipart part (ref s3_filesys.cc:646)
_MAX_RETRY = 3


class _RetryableStatus(OSError):
    """A 5xx/429 response re-raised through the retry machinery.  Subclasses
    ``OSError`` so the default retryable predicate sees it; carries the full
    response so retry exhaustion can still RETURN it (the caller contract:
    non-transport failures come back as a status, not an exception), and the
    server's ``Retry-After`` as the ``retry_after_s`` backoff-floor hint that
    :meth:`RetryPolicy.call` honors (clamped at the remaining deadline)."""

    def __init__(self, status: int, hdrs: Dict[str, str], data: bytes,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(f"HTTP {status}")
        self.status = status
        self.hdrs = hdrs
        self.data = data
        self.retry_after_s = retry_after_s


def _parse_retry_after(hdrs: Dict[str, str]) -> Optional[float]:
    """``Retry-After`` → seconds; both RFC forms (delta-seconds, HTTP-date)."""
    ra = hdrs.get("retry-after")
    if ra is None:
        return None
    try:
        return max(0.0, float(ra))
    except ValueError:
        pass
    try:
        t = email.utils.parsedate_to_datetime(ra)
        now = _dt.datetime.now(_dt.timezone.utc)
        if t.tzinfo is None:
            t = t.replace(tzinfo=_dt.timezone.utc)
        return max(0.0, (t - now).total_seconds())
    except (TypeError, ValueError):
        return None


def _http_request(scheme: str, netloc: str, method: str, path_qs: str,
                  headers: Dict[str, str], body: bytes = b"",
                  timeout: float = 60.0,
                  retries: Optional[int] = None,
                  deadline: Optional[Deadline] = None
                  ) -> Tuple[int, Dict[str, str], bytes]:
    """One HTTP round trip under the shared retry machinery
    (:class:`~dmlc_core_tpu.utils.retry.RetryPolicy`: exponential backoff,
    full jitter, ``DMLC_IO_*`` env knobs, ``retry.io.http.*`` counters).

    Transport errors, 5xx and 429 are retried; 429's ``Retry-After`` raises
    the backoff floor (capped at the remaining ``DMLC_IO_DEADLINE`` budget).
    By default only idempotent methods retry (a retried POST/PUT could
    double-apply or fail after server-side success — e.g. re-sending
    CompleteMultipartUpload for an already-completed id).  Callers that KNOW
    a write is idempotent (UploadPart: same partNumber+uploadId replaces the
    part; InitiateMultipartUpload: a lost-response orphan id is
    lifecycle-cleaned) pass ``retries`` explicitly — the write-side analog
    of restart-on-seek (`s3_filesys.cc:747-799`).

    Each attempt crosses the ``s3.request`` fault-injection probe, so drops/
    latency/5xx schedules from ``DMLC_FAULT_SPEC`` exercise this exact path.
    """
    if retries is None:
        retries = (get_env("DMLC_IO_RETRIES", _MAX_RETRY)
                   if method in ("GET", "HEAD") else 1)
    if deadline is None:
        budget = get_env("DMLC_IO_DEADLINE", 0.0)
        deadline = Deadline(budget if budget > 0 else None)
    policy = RetryPolicy(
        max_attempts=retries,
        base_delay_s=get_env("DMLC_IO_BACKOFF_BASE", 0.1),
        max_delay_s=get_env("DMLC_IO_BACKOFF_MAX", 2.0),
        retryable=lambda e: isinstance(
            e, (OSError, http.client.HTTPException)),
        name="io.http")

    def _once() -> Tuple[int, Dict[str, str], bytes]:
        fault_point("s3.request")
        conn = None
        try:
            cls = (http.client.HTTPSConnection if scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(netloc, timeout=deadline.clamp(timeout))
            conn.request(method, path_qs, body=body or None, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            hdrs = {k.lower(): v for k, v in resp.getheaders()}
        finally:
            if conn is not None:
                conn.close()
        if resp.status >= 500 or resp.status == 429:
            raise _RetryableStatus(resp.status, hdrs, data,
                                   _parse_retry_after(hdrs))
        return resp.status, hdrs, data

    try:
        return policy.call(_once, deadline=deadline)
    except (RetriesExhausted, DeadlineExpired) as e:
        cause = e.__cause__
        if isinstance(cause, _RetryableStatus):
            # exhausted on a retryable STATUS: hand the caller the final
            # response, same contract as the old hand-rolled loop
            return cause.status, cause.hdrs, cause.data
        raise DMLCError(
            f"http {method} {netloc}{path_qs} failed: {cause or e}") from e


class RangedReadStream(io.RawIOBase):
    """Seekable read stream over HTTP Range GETs with restart-on-seek.

    The ``CURLReadStreamBase`` design (`s3_filesys.cc:219-361`): a buffer is
    filled by ranged GETs starting at ``curr_bytes_``; ``Seek`` outside the
    buffered window discards state and restarts the transfer at the new
    offset (`s3_filesys.cc:234-239`). Subclasses provide
    :meth:`_request_headers` to sign each range request.
    """

    def __init__(self, scheme: str, netloc: str, path_qs: str,
                 size: Optional[int] = None,
                 buffer_size: int = _DEFAULT_BUFFER) -> None:
        super().__init__()
        self._scheme = scheme
        self._netloc = netloc
        self._path_qs = path_qs
        self._buffer_size = buffer_size
        self._size = size          # lazily discovered from Content-Range
        self._pos = 0              # logical read position
        self._buf = b""
        self._buf_start = 0        # file offset of self._buf[0]

    # subclass hook: per-request auth headers (S3 signs every range request)
    def _request_headers(self, method: str,
                         headers: Dict[str, str]) -> Dict[str, str]:
        return headers

    def _fetch(self, start: int, end_excl: int) -> bytes:
        headers = {"Range": f"bytes={start}-{end_excl - 1}"}
        headers = self._request_headers("GET", headers)
        status, hdrs, data = _http_request(
            self._scheme, self._netloc, "GET", self._path_qs, headers)
        if status == 206:
            cr = hdrs.get("content-range", "")
            if "/" in cr and self._size is None:
                try:
                    self._size = int(cr.rsplit("/", 1)[1])
                except ValueError:
                    pass
            return data
        if status == 200:
            # server ignored Range: we now hold the whole object — keep it
            # all as the buffer so we never re-download it per refill
            if self._size is None:
                self._size = len(data)
            self._buf = data
            self._buf_start = 0
            return data[start:end_excl]
        if status in (404, 403):
            raise DMLCError(
                f"GET {self._netloc}{self._path_qs}: HTTP {status}")
        if status == 416:           # requested range beyond EOF
            return b""
        raise DMLCError(
            f"GET {self._netloc}{self._path_qs} range {start}-{end_excl}: "
            f"HTTP {status}")

    # -- io.RawIOBase interface --------------------------------------------
    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def tell(self) -> int:
        return self._pos

    def _length(self) -> int:
        if self._size is None:
            headers = self._request_headers("HEAD", {})
            status, hdrs, _ = _http_request(
                self._scheme, self._netloc, "HEAD", self._path_qs, headers)
            if status != 200 or "content-length" not in hdrs:
                # fall back: probe with a 1-byte range GET
                self._fetch(0, 1)
                if self._size is None:
                    raise DMLCError(
                        f"cannot determine size of {self._netloc}{self._path_qs}")
            else:
                self._size = int(hdrs["content-length"])
        return self._size

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            new = offset
        elif whence == os.SEEK_CUR:
            new = self._pos + offset
        elif whence == os.SEEK_END:
            new = self._length() + offset
        else:
            raise ValueError(f"bad whence {whence}")
        check(new >= 0, "negative seek position")
        # restart-on-seek: outside the buffered window → drop buffer
        if not (self._buf_start <= new <= self._buf_start + len(self._buf)):
            self._buf = b""
            self._buf_start = new
        self._pos = new
        return self._pos

    def readinto(self, b) -> int:
        want = len(b)
        if want == 0:
            return 0
        off = self._pos - self._buf_start
        if not (0 <= off < len(self._buf)):
            # refill buffer at current position
            if self._size is not None and self._pos >= self._size:
                return 0
            fill = max(self._buffer_size, want)
            data = self._fetch(self._pos, self._pos + fill)
            if not data:
                return 0
            # a 200-fallback (server ignored Range) leaves the WHOLE object
            # in self._buf — recompute the window instead of clobbering it,
            # or each refill would re-download the full object
            off = self._pos - self._buf_start
            if not (0 <= off < len(self._buf)):
                self._buf = data
                self._buf_start = self._pos
                off = 0
        n = min(want, len(self._buf) - off)
        b[:n] = self._buf[off:off + n]
        self._pos += n
        return n

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            chunks = []
            while True:
                c = super().read(self._buffer_size)
                if not c:
                    return b"".join(chunks)
                chunks.append(c)
        return super().read(n) or b""


# ---------------------------------------------------------------------------
# http:// / https:// — read-only remote files (ref HttpReadStream
# s3_filesys.cc:533-549: unsigned ranged reads over any URL)
# ---------------------------------------------------------------------------

class HttpFileSystem(FileSystem):
    """Read-only FS over plain HTTP(S) (reference `s3_filesys.cc:533-549`)."""

    def __init__(self, scheme: str = "http") -> None:
        self._scheme = scheme

    def get_path_info(self, uri: URI) -> FileInfo:
        status, hdrs, _ = _http_request(self._scheme, uri.host, "HEAD",
                                        uri.name or "/", {})
        if status != 200:
            raise DMLCError(f"HEAD {uri.raw}: HTTP {status}")
        if "content-length" in hdrs:
            size = int(hdrs["content-length"])
        else:
            # chunked/dynamic responses omit Content-Length; a zero size
            # would silently drop the file from input splits — probe instead
            s = RangedReadStream(self._scheme, uri.host, uri.name or "/")
            size = s._length()
        return FileInfo(path=uri.raw, size=size, type="file")

    def list_directory(self, uri: URI) -> List[FileInfo]:
        raise DMLCError("HttpFileSystem does not support listing")

    def open(self, uri: URI, mode: str) -> BinaryIO:
        check(mode == "r", "http(s):// is read-only")
        return RangedReadStream(self._scheme, uri.host, uri.name or "/")


# ---------------------------------------------------------------------------
# AWS Signature Version 4
# ---------------------------------------------------------------------------

def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac_sha256(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(method: str, host: str, path: str,
            query: Dict[str, str], headers: Dict[str, str],
            payload_hash: str, region: str, service: str,
            access_key: str, secret_key: str,
            session_token: Optional[str] = None,
            now: Optional[_dt.datetime] = None,
            include_content_sha256: bool = True) -> Dict[str, str]:
    """AWS SigV4: returns ``headers`` + ``Authorization``/``x-amz-*``.

    The reference signs with v2 HMAC-SHA1 (`s3_filesys.cc:90-121`); modern
    S3/GCS-interop require v4. Canonicalization follows the official spec:
    sorted URL-encoded query, sorted lowercase signed headers, hex payload
    hash; signing key = HMAC chain over date/region/service.
    """
    now = now or _dt.datetime.now(_dt.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    headers = dict(headers)
    headers["host"] = host
    headers["x-amz-date"] = amz_date
    if include_content_sha256:      # S3 requires it; the generic AWS
        headers["x-amz-content-sha256"] = payload_hash  # test suite omits it
    if session_token:
        headers["x-amz-security-token"] = session_token

    canonical_uri = urllib.parse.quote(path, safe="/")
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(query.items()))
    lower = {k.lower(): v.strip() for k, v in headers.items()}
    signed_headers = ";".join(sorted(lower))
    canonical_headers = "".join(f"{k}:{lower[k]}\n" for k in sorted(lower))
    canonical_request = "\n".join([
        method, canonical_uri, canonical_query, canonical_headers,
        signed_headers, payload_hash])

    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        _sha256_hex(canonical_request.encode())])

    k_date = _hmac_sha256(b"AWS4" + secret_key.encode(), datestamp)
    k_region = _hmac_sha256(k_date, region)
    k_service = _hmac_sha256(k_region, service)
    k_signing = _hmac_sha256(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()

    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return headers


# ---------------------------------------------------------------------------
# S3 (and S3-compatible endpoints: minio, GCS interop, fake test servers)
# ---------------------------------------------------------------------------

class _S3Config:
    """Credentials/endpoint from env (reference ctor `s3_filesys.cc:926`:
    AWS_ACCESS_KEY_ID/SECRET/SESSION_TOKEN/REGION + custom endpoint)."""

    def __init__(self, scheme_env_prefix: str = "AWS",
                 service: str = "s3") -> None:
        env = os.environ
        self.access_key = env.get(f"{scheme_env_prefix}_ACCESS_KEY_ID", "")
        self.secret_key = env.get(f"{scheme_env_prefix}_SECRET_ACCESS_KEY", "")
        self.session_token = env.get(f"{scheme_env_prefix}_SESSION_TOKEN") or None
        self.region = (env.get(f"{scheme_env_prefix}_REGION")
                       or env.get(f"{scheme_env_prefix}_DEFAULT_REGION")
                       or "us-east-1")
        self.endpoint = env.get("DMLC_S3_ENDPOINT") or env.get("S3_ENDPOINT") or ""
        self.service = service

    def resolve(self, bucket: str) -> Tuple[str, str, str]:
        """-> (scheme, netloc, path_prefix). Custom endpoints use path-style
        addressing (bucket in the path) so local fake servers/minio work."""
        if self.endpoint:
            ep = self.endpoint
            if "://" not in ep:        # "localhost:9000" minio-style form
                ep = "http://" + ep
            p = urllib.parse.urlparse(ep)
            return p.scheme or "http", p.netloc, f"/{bucket}"
        return "https", f"{bucket}.s3.{self.region}.amazonaws.com", ""


class _S3ReadStream(RangedReadStream):
    """Signed ranged-read stream (reference ``s3::ReadStream``
    `s3_filesys.cc:462-530`: every range fill re-signs the request)."""

    def __init__(self, cfg: _S3Config, bucket: str, key: str,
                 size: Optional[int] = None) -> None:
        scheme, netloc, prefix = cfg.resolve(bucket)
        path = f"{prefix}/{key}"
        # wire path must be the same percent-encoded bytes sign_v4 signs
        super().__init__(scheme, netloc, urllib.parse.quote(path, safe="/"),
                         size=size)
        self._cfg = cfg
        self._sign_path = path

    def _request_headers(self, method: str,
                         headers: Dict[str, str]) -> Dict[str, str]:
        if not self._cfg.access_key:
            return headers
        return sign_v4(method,
                       self._netloc, self._sign_path, {}, headers,
                       _sha256_hex(b""), self._cfg.region, self._cfg.service,
                       self._cfg.access_key, self._cfg.secret_key,
                       self._cfg.session_token)


class _S3WriteStream(io.RawIOBase):
    """Multipart-upload write stream (reference ``s3::WriteStream``
    `s3_filesys.cc:551-799`): buffer ≥5MB, InitiateMultipartUpload on first
    flush, UploadPart per buffer, CompleteMultipartUpload XML on close;
    small objects fall back to a single PUT."""

    def __init__(self, fs: "S3FileSystem", bucket: str, key: str,
                 part_size: int = _MIN_PART_SIZE) -> None:
        super().__init__()
        self._fs = fs
        self._bucket = bucket
        self._key = key
        self._part_size = max(part_size, 1)
        self._buf = bytearray()
        self._upload_id: Optional[str] = None
        self._etags: List[str] = []
        self._closed = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf.extend(b)
        while len(self._buf) >= self._part_size:
            self._flush_part(bytes(self._buf[:self._part_size]))
            del self._buf[:self._part_size]
        return len(b)

    def _flush_part(self, data: bytes) -> None:
        # Initiate and UploadPart retry on dropped connections / 5xx
        # (reference `s3_filesys.cc:747-799` loops its curl calls the same
        # way): re-PUTting partNumber+uploadId replaces the part — exactly
        # idempotent — and a lost Initiate response only orphans an id for
        # lifecycle cleanup.  CompleteMultipartUpload (close) stays
        # single-shot: a blind re-send after server-side success returns
        # NoSuchUpload and would turn a succeeded publish into an error.
        if self._upload_id is None:
            status, _, body = self._fs._request(
                "POST", self._bucket, self._key, {"uploads": ""}, b"",
                retries=_MAX_RETRY)
            check(status == 200, f"InitiateMultipartUpload: HTTP {status}")
            self._upload_id = ET.fromstring(body).findtext(".//{*}UploadId")
            check(bool(self._upload_id), "no UploadId in response")
        part_no = len(self._etags) + 1
        status, hdrs, _ = self._fs._request(
            "PUT", self._bucket, self._key,
            {"partNumber": str(part_no), "uploadId": self._upload_id}, data,
            retries=_MAX_RETRY)
        check(status == 200, f"UploadPart {part_no}: HTTP {status}")
        self._etags.append(hdrs.get("etag", f'"{part_no}"'))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._upload_id is None:
            # small object: single PUT (reference same fallback, :747)
            status, _, _ = self._fs._request(
                "PUT", self._bucket, self._key, {}, bytes(self._buf))
            check(status == 200, f"PUT object: HTTP {status}")
        else:
            if self._buf:
                self._flush_part(bytes(self._buf))
                self._buf.clear()
            parts = "".join(
                f"<Part><PartNumber>{i + 1}</PartNumber><ETag>{etag}</ETag></Part>"
                for i, etag in enumerate(self._etags))
            xml_body = (f"<CompleteMultipartUpload>{parts}"
                        f"</CompleteMultipartUpload>").encode()
            status, _, _ = self._fs._request(
                "POST", self._bucket, self._key,
                {"uploadId": self._upload_id}, xml_body)
            check(status == 200, f"CompleteMultipartUpload: HTTP {status}")
        super().close()

    def abort(self) -> None:
        """Abandon the write WITHOUT publishing: callers that hit an error
        mid-write call this instead of close(), so a partial buffer never
        becomes the object (AbortMultipartUpload when one is open)."""
        if self._closed:
            return
        self._closed = True
        self._buf.clear()
        if self._upload_id is not None:
            try:
                self._fs._request("DELETE", self._bucket, self._key,
                                  {"uploadId": self._upload_id}, b"")
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        super().close()


class S3FileSystem(FileSystem):
    """``s3://bucket/key`` object store (reference `s3_filesys.cc`)."""

    def __init__(self, env_prefix: str = "AWS", service: str = "s3",
                 part_size: int = _MIN_PART_SIZE) -> None:
        self._env_prefix = env_prefix
        self._service = service
        self._part_size = part_size

    @property
    def cfg(self) -> _S3Config:
        # re-read env per request (cheap: six dict lookups) so credentials
        # and endpoint can change after the scheme singletons are created
        return _S3Config(self._env_prefix, self._service)

    def _request(self, method: str, bucket: str, key: str,
                 query: Dict[str, str], body: bytes,
                 retries: Optional[int] = None
                 ) -> Tuple[int, Dict[str, str], bytes]:
        cfg = self.cfg
        scheme, netloc, prefix = cfg.resolve(bucket)
        path = f"{prefix}/{key}" if key else (prefix or "/")
        headers: Dict[str, str] = {}
        if cfg.access_key:
            headers = sign_v4(method, netloc, path, query, headers,
                              _sha256_hex(body), cfg.region, cfg.service,
                              cfg.access_key, cfg.secret_key,
                              cfg.session_token)
        # encode path+query exactly as sign_v4 canonicalized them, or the
        # server-side signature check would see different bytes
        qs = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(query.items()))
        wire_path = urllib.parse.quote(path, safe="/")
        path_qs = f"{wire_path}?{qs}" if qs else wire_path
        return _http_request(scheme, netloc, method, path_qs, headers, body,
                             retries=retries)

    @staticmethod
    def _split(uri: URI) -> Tuple[str, str]:
        return uri.host, uri.name.lstrip("/")

    def get_path_info(self, uri: URI) -> FileInfo:
        bucket, key = self._split(uri)
        # empty key = bucket root: HEAD would be HeadBucket (200) and
        # misreport a zero-size file — go straight to the prefix probe
        if key:
            status, hdrs, _ = self._request("HEAD", bucket, key, {}, b"")
            if status == 200:
                return FileInfo(path=uri.raw,
                                size=int(hdrs.get("content-length", 0)),
                                type="file")
        else:
            status = 404
        # directory probe: any object under the prefix? (ref TryGetPathInfo)
        infos = self.list_directory(uri)
        if infos:
            return FileInfo(path=uri.raw, size=0, type="dir")
        raise DMLCError(f"s3: no such object {uri.raw} (HTTP {status})")

    def delete(self, uri: URI) -> None:
        bucket, key = self._split(uri)
        status, _, _ = self._request("DELETE", bucket, key, {}, b"")
        # S3 DeleteObject: 204 on success (idempotent — deleting a missing
        # key also returns 204)
        check(status in (200, 204), f"s3 DELETE {uri.raw}: HTTP {status}")

    def list_directory(self, uri: URI) -> List[FileInfo]:
        bucket, key = self._split(uri)
        prefix = key if not key or key.endswith("/") else key + "/"
        out: List[FileInfo] = []
        token: Optional[str] = None
        while True:
            q = {"list-type": "2", "prefix": prefix, "delimiter": "/"}
            if token:
                q["continuation-token"] = token
            status, _, body = self._request("GET", bucket, "", q, b"")
            check(status == 200, f"ListObjectsV2: HTTP {status}")
            root = ET.fromstring(body)

            def _find(el, tag):
                return el.findtext(f"{{*}}{tag}") or el.findtext(tag)

            for c in list(root.iter()):
                if c.tag.endswith("Contents"):
                    k = _find(c, "Key")
                    if k and k != prefix:
                        out.append(FileInfo(
                            path=f"{uri.protocol}{bucket}/{k}",
                            size=int(_find(c, "Size") or 0), type="file"))
                elif c.tag.endswith("CommonPrefixes"):
                    p = _find(c, "Prefix")
                    if p:
                        out.append(FileInfo(
                            path=f"{uri.protocol}{bucket}/{p.rstrip('/')}",
                            size=0, type="dir"))
            token = (root.findtext("{*}NextContinuationToken")
                     or root.findtext("NextContinuationToken"))
            if not token:
                return out

    def open(self, uri: URI, mode: str) -> BinaryIO:
        bucket, key = self._split(uri)
        if mode == "r":
            return _S3ReadStream(self.cfg, bucket, key)
        check(mode == "w", "s3 supports modes 'r' and 'w' only")
        return _S3WriteStream(self, bucket, key, self._part_size)


class GCSFileSystem(S3FileSystem):
    """``gs://`` via the GCS S3-compatible XML API with HMAC interop keys
    (env ``GCS_ACCESS_KEY_ID``/``GCS_SECRET_ACCESS_KEY``; endpoint
    ``https://storage.googleapis.com`` unless ``DMLC_S3_ENDPOINT`` is set).
    TPU-idiomatic object store — plays the role S3 plays in the reference."""

    def __init__(self) -> None:
        super().__init__(env_prefix="GCS", service="s3")

    @property
    def cfg(self) -> _S3Config:
        c = _S3Config("GCS", "s3")
        # a custom *S3* endpoint (minio etc.) must not reroute gs:// traffic;
        # only the GCS-specific override applies here
        c.endpoint = (get_env("DMLC_GCS_ENDPOINT", None)
                      or "https://storage.googleapis.com")
        return c


# ---------------------------------------------------------------------------
# WebHDFS
# ---------------------------------------------------------------------------

def _request_url(method: str, url: str,
                 body: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
    p = urllib.parse.urlparse(url)
    path_qs = p.path + (f"?{p.query}" if p.query else "")
    return _http_request(p.scheme or "http", p.netloc, method, path_qs,
                         {}, body)


def _webhdfs_location(status: int, hdrs: Dict[str, str],
                      data: bytes) -> Optional[str]:
    """Two-step WebHDFS data ops: the namenode answers OPEN/CREATE either
    with a 307 redirect or (with ``noredirect=true``) a JSON body holding
    the datanode ``Location``; data flows to/from that second URL."""
    if status == 307:
        return hdrs.get("location")
    if status == 200 and "json" in hdrs.get("content-type", ""):
        import json as _json
        try:
            return _json.loads(data).get("Location")
        except (ValueError, AttributeError):
            return None
    return None


class _WebHDFSReadStream(RangedReadStream):
    """Ranged reads via ``OPEN&offset=..&length=..`` (maps the reference's
    hdfsPread positional read, `hdfs_filesys.cc:31-55`, onto REST)."""

    def __init__(self, scheme: str, netloc: str, path: str, size: int,
                 auth: Dict[str, str]) -> None:
        super().__init__(scheme, netloc, path, size=size)
        self._auth = auth

    def _fetch(self, start: int, end_excl: int) -> bytes:
        q = {"op": "OPEN", "offset": str(start),
             "length": str(end_excl - start), "noredirect": "true"}
        q.update(self._auth)
        qs = urllib.parse.urlencode(q)
        status, hdrs, data = _http_request(
            self._scheme, self._netloc, "GET", f"{self._path_qs}?{qs}", {})
        loc = _webhdfs_location(status, hdrs, data)
        if loc is not None:
            # namenode handed us the datanode URL — fetch the bytes there
            status, _, data = _request_url("GET", loc)
        if status != 200:
            raise DMLCError(f"webhdfs OPEN {self._path_qs}: HTTP {status}")
        return data


class WebHDFSFileSystem(FileSystem):
    """``hdfs://host:port/path`` over WebHDFS REST (reference wraps libhdfs
    JNI, `hdfs_filesys.cc`; same surface, no JVM dependency).

    Env: ``DMLC_WEBHDFS_SCHEME`` (default http), ``HADOOP_USER_NAME``,
    ``DMLC_WEBHDFS_TOKEN`` — a Hadoop delegation token appended as
    ``delegation=`` to every request.  This is the standard way into a
    kerberized cluster without SPNEGO on the client: obtain the token
    out-of-band (``hdfs fetchdt`` after kinit, or from the YARN AM's
    credentials) and export it.  When the token is set, ``user.name`` is
    omitted — Hadoop rejects requests carrying both.
    The URI host is the namenode ``host:port`` (reference connect,
    `hdfs_filesys.cc:94`).
    """

    def _base(self, uri: URI) -> Tuple[str, str, str]:
        scheme = get_env("DMLC_WEBHDFS_SCHEME", "http")
        path = urllib.parse.quote(uri.name, safe="/")
        return scheme, uri.host, f"/webhdfs/v1{path}"

    @staticmethod
    def _auth_params() -> Dict[str, str]:
        """delegation token > user.name > nothing (simple-auth clusters)."""
        token = get_env("DMLC_WEBHDFS_TOKEN", None)
        if token:
            return {"delegation": token}
        user = os.environ.get("HADOOP_USER_NAME")
        return {"user.name": user} if user else {}

    def _op(self, uri: URI, method: str, op: str,
            extra: Optional[Dict[str, str]] = None,
            body: bytes = b"") -> Tuple[int, Dict[str, str], bytes]:
        scheme, netloc, path = self._base(uri)
        q = {"op": op}
        q.update(self._auth_params())
        q.update(extra or {})
        qs = urllib.parse.urlencode(q)
        return _http_request(scheme, netloc, method, f"{path}?{qs}", {}, body)

    @staticmethod
    def _info_from_status(uri_prefix: str, name: str, st: dict) -> FileInfo:
        path = uri_prefix if not name else f"{uri_prefix.rstrip('/')}/{name}"
        return FileInfo(path=path, size=int(st.get("length", 0)),
                        type="dir" if st.get("type") == "DIRECTORY" else "file")

    def get_path_info(self, uri: URI) -> FileInfo:
        import json as _json
        status, _, body = self._op(uri, "GET", "GETFILESTATUS")
        if status != 200:
            raise DMLCError(f"webhdfs GETFILESTATUS {uri.raw}: HTTP {status}")
        st = _json.loads(body)["FileStatus"]
        return self._info_from_status(uri.raw, "", st)

    def list_directory(self, uri: URI) -> List[FileInfo]:
        import json as _json
        status, _, body = self._op(uri, "GET", "LISTSTATUS")
        if status != 200:
            raise DMLCError(f"webhdfs LISTSTATUS {uri.raw}: HTTP {status}")
        sts = _json.loads(body)["FileStatuses"]["FileStatus"]
        return [self._info_from_status(uri.raw, st.get("pathSuffix", ""), st)
                for st in sts]

    def rename(self, src: URI, dst: URI) -> None:
        """``op=RENAME`` — atomic within HDFS (`FileSystem.rename`); the
        publish step for write-to-temp checkpoint objects."""
        status, _, _ = self._op(
            src, "PUT", "RENAME",
            {"destination": "/" + dst.name.lstrip("/")})
        check(status == 200, f"webhdfs RENAME {src.raw}: HTTP {status}")

    def delete(self, uri: URI) -> None:
        status, _, _ = self._op(uri, "DELETE", "DELETE")
        check(status == 200, f"webhdfs DELETE {uri.raw}: HTTP {status}")

    def open(self, uri: URI, mode: str) -> BinaryIO:
        if mode == "r":
            info = self.get_path_info(uri)
            scheme, netloc, path = self._base(uri)
            return _WebHDFSReadStream(scheme, netloc, path, info.size,
                                      self._auth_params())
        check(mode == "w", "webhdfs supports modes 'r' and 'w' only")
        part = env_int("DMLC_WEBHDFS_PART_SIZE", 8 << 20, minimum=1)
        return _WebHDFSWriteStream(self, uri, max(1, part))


class _WebHDFSWriteStream(io.BufferedIOBase):
    """Streaming writer: CREATE carries the first part, each further part
    goes out as an APPEND — memory stays bounded at ``part_size`` no matter
    how large the object (the reference streams via hdfsWrite,
    `hdfs_filesys.cc:56-75`; buffering the whole object, as v1 did, OOMs on
    large checkpoint writes)."""

    def __init__(self, fs: "WebHDFSFileSystem", uri: URI,
                 part_size: int) -> None:
        self._fs = fs
        self._uri = uri
        self._part = part_size
        self._buf = bytearray()
        self._created = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        if self.closed:
            raise ValueError("I/O operation on closed file")
        self._buf += b
        while len(self._buf) >= self._part:
            self._send(bytes(self._buf[:self._part]))
            del self._buf[:self._part]
        return len(b)

    def _send(self, data: bytes) -> None:
        if not self._created:
            # step 1: namenode CREATE (no body) → datanode Location
            status, hdrs, resp = self._fs._op(
                self._uri, "PUT", "CREATE",
                {"overwrite": "true", "noredirect": "true"}, b"")
            loc = _webhdfs_location(status, hdrs, resp)
            if loc is not None:
                # step 2: stream the first part to the datanode
                status, _, _ = _request_url("PUT", loc, data)
            elif status in (200, 201):
                # gateway (e.g. HttpFS) accepted data directly
                status, _, _ = self._fs._op(
                    self._uri, "PUT", "CREATE",
                    {"overwrite": "true", "noredirect": "true"}, data)
            check(status in (200, 201), f"webhdfs CREATE: HTTP {status}")
            self._created = True
            return
        status, hdrs, resp = self._fs._op(self._uri, "POST", "APPEND",
                                          {"noredirect": "true"}, b"")
        loc = _webhdfs_location(status, hdrs, resp)
        if loc is not None:
            status, _, _ = _request_url("POST", loc, data)
        elif status in (200, 201, 204):
            status, _, _ = self._fs._op(self._uri, "POST", "APPEND", {}, data)
        check(status in (200, 201, 204), f"webhdfs APPEND: HTTP {status}")

    def abort(self) -> None:
        """Drop buffered bytes and close without flushing.  NOTE: parts
        already APPENDed are visible at the target path (WebHDFS has no
        upload session) — atomic publish over hdfs:// therefore needs
        write-to-temp + :meth:`WebHDFSFileSystem.rename`, which is what
        the checkpoint layer does."""
        if not self.closed:
            self._buf = bytearray()
            self._created = True    # suppress the empty-file CREATE
            super().close()

    def close(self) -> None:
        if not self.closed:
            # final short part; an empty file still needs its CREATE
            if self._buf or not self._created:
                self._send(bytes(self._buf))
                self._buf = bytearray()
            super().close()


# ---------------------------------------------------------------------------
# Azure (listing-only, like the reference azure_filesys.cc:42-80)
# ---------------------------------------------------------------------------

class AzureFileSystem(FileSystem):
    """``azure://account/container/path`` blob listing via the public List
    Blobs REST API (reference backend is also listing-only; its Open is
    unimplemented, `azure_filesys.cc`). Env: ``AZURE_STORAGE_ENDPOINT`` to
    override the host (for tests), ``AZURE_STORAGE_SAS`` appended as auth."""

    def _endpoint(self, account: str) -> Tuple[str, str]:
        ep = os.environ.get("AZURE_STORAGE_ENDPOINT", "")
        if ep:
            p = urllib.parse.urlparse(ep)
            return p.scheme or "http", p.netloc
        return "https", f"{account}.blob.core.windows.net"

    def list_directory(self, uri: URI) -> List[FileInfo]:
        account = uri.host
        parts = uri.name.lstrip("/").split("/", 1)
        container = parts[0]
        prefix = parts[1] if len(parts) > 1 else ""
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        scheme, netloc = self._endpoint(account)
        sas = os.environ.get("AZURE_STORAGE_SAS", "")
        out: List[FileInfo] = []
        marker = ""
        while True:     # follow NextMarker pagination (5000 blobs per page)
            q = {"restype": "container", "comp": "list", "prefix": prefix,
                 "delimiter": "/"}
            if marker:
                q["marker"] = marker
            qs = urllib.parse.urlencode(q) + (
                f"&{sas.lstrip('?&')}" if sas else "")
            status, _, body = _http_request(scheme, netloc, "GET",
                                            f"/{container}?{qs}", {})
            check(status == 200, f"azure List Blobs: HTTP {status}")
            root = ET.fromstring(body)
            for b in root.iter():
                if b.tag.endswith("Blob"):
                    name = b.findtext("Name") or b.findtext("{*}Name") or ""
                    size = b.findtext(".//Content-Length") or "0"
                    out.append(FileInfo(
                        path=f"azure://{account}/{container}/{name}",
                        size=int(size), type="file"))
                elif b.tag.endswith("BlobPrefix"):
                    name = b.findtext("Name") or ""
                    out.append(FileInfo(
                        path=f"azure://{account}/{container}/{name.rstrip('/')}",
                        size=0, type="dir"))
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return out

    def get_path_info(self, uri: URI) -> FileInfo:
        raise DMLCError("AzureFileSystem is listing-only (as the reference)")

    def open(self, uri: URI, mode: str) -> BinaryIO:
        raise DMLCError("AzureFileSystem is listing-only (as the reference)")


# -- scheme registration (reference io.cc:31-60) -----------------------------
_http_fs = HttpFileSystem("http")
_https_fs = HttpFileSystem("https")
_s3_fs = S3FileSystem()
_gcs_fs = GCSFileSystem()
_hdfs_fs = WebHDFSFileSystem()
_azure_fs = AzureFileSystem()

FS_REGISTRY.register("http", description="HTTP read-only")(lambda: _http_fs)
FS_REGISTRY.register("https", description="HTTPS read-only")(lambda: _https_fs)
FS_REGISTRY.register("s3", description="S3 object store")(lambda: _s3_fs)
FS_REGISTRY.register("gs", description="GCS (S3-compat XML API)")(lambda: _gcs_fs)
FS_REGISTRY.register("hdfs", description="WebHDFS")(lambda: _hdfs_fs)
FS_REGISTRY.register("azure", description="Azure blob (listing)")(lambda: _azure_fs)
