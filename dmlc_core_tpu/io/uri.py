"""URI parsing and datasource URI sugar — capability parity with reference
``src/io/uri_spec.h`` and the ``URI`` struct in ``src/io/filesys.h:18-52``.

Reference semantics:

* ``URI{protocol, host, name}``: ``protocol`` includes the trailing ``://``
  (empty for bare paths), ``host`` is the authority (bucket/namenode), and
  ``name`` the path within it (`filesys.h:24-52`).
* ``URISpec`` adds datasource sugar (`uri_spec.h:29-77`)::

      path?format=libsvm&key=value#cachefile

  query args become per-datasource config, and the fragment names a cache
  file which gets a ``.splitN.partK`` suffix per partition (`uri_spec.h:51-54`).
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["URI", "URISpec"]


class URI:
    """Split ``proto://host/path`` (reference ``URI`` `filesys.h:18-52`)."""

    def __init__(self, uri: str):
        self.raw = uri
        pos = uri.find("://")
        if pos < 0:
            self.protocol = ""
            self.host = ""
            self.name = uri
            return
        self.protocol = uri[: pos + 3]  # includes '://', as in the reference
        rest = uri[pos + 3:]
        slash = rest.find("/")
        if slash < 0:
            self.host = rest
            self.name = ""
        else:
            self.host = rest[:slash]
            self.name = rest[slash:]

    @property
    def scheme(self) -> str:
        """Protocol without '://' ('' for local paths)."""
        return self.protocol[:-3] if self.protocol else ""

    def str_nohost(self) -> str:
        """Reconstruct without authority (local path form)."""
        return self.protocol + self.name if self.protocol else self.name

    def __str__(self) -> str:
        return self.raw

    def __repr__(self) -> str:
        return f"URI(protocol={self.protocol!r}, host={self.host!r}, name={self.name!r})"


class URISpec:
    """Datasource URI sugar ``path?k=v&k2=v2#cachefile`` (reference `uri_spec.h:29-77`)."""

    def __init__(self, uri: str, part_index: int = 0, num_parts: int = 1):
        self.raw = uri
        self.args: Dict[str, str] = {}
        self.cache_file: Optional[str] = None

        body = uri
        frag = body.find("#")
        if frag >= 0:
            cache = body[frag + 1:]
            body = body[:frag]
            if cache:
                # per-partition cache suffix (reference `uri_spec.h:51-54`)
                if num_parts != 1:
                    cache = f"{cache}.split{num_parts}.part{part_index}"
                self.cache_file = cache
        q = body.find("?")
        if q >= 0:
            query = body[q + 1:]
            body = body[:q]
            for kv in query.split("&"):
                if not kv:
                    continue
                if "=" in kv:
                    k, v = kv.split("=", 1)
                else:
                    k, v = kv, ""
                self.args[k] = v
        self.uri = body

    def __repr__(self) -> str:
        return (f"URISpec(uri={self.uri!r}, args={self.args!r}, "
                f"cache_file={self.cache_file!r})")
