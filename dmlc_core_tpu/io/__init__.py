"""I/O layer: URI-addressed streams, filesystems, recordio codec, and the
partition-correct InputSplit engine (reference ``src/io/``, SURVEY §2.2-2.3).

Factory entry point :func:`create_input_split` mirrors reference
``InputSplit::Create`` (`io.h:241-281`, impl `src/io.cc:70-119`):

* ``type``: ``"text"``/``"line"`` (line records), ``"recordio"``,
  ``"indexed_recordio"``, ``"stdin"``;
* by default the split is wrapped in a background chunk-prefetch thread
  (reference wraps ThreadedInputSplit when C++11, `io.cc:108-111`);
* URI sugar: ``path?k=v#cachefile`` — a fragment selects an on-disk chunk
  cache (reference `io.cc:109-113`), with per-partition suffixing;
* ``shuffle=True`` over-partitions and visits sub-parts in random per-epoch
  order (reference ``InputSplitShuffle::Create`` `input_split_shuffle.h:137`).
"""

from __future__ import annotations

from typing import Optional

from ..utils import DMLCError, check
from .uri import URI, URISpec
from .filesys import (FileInfo, FileSystem, LocalFileSystem, FS_REGISTRY,
                      get_filesystem, open_stream, open_seek_stream_for_read,
                      list_directory_recursive)
from .recordio import (KMAGIC, RecordIOWriter, RecordIOReader,
                       RecordIOChunkReader, encode_lrec, decode_lrec)
from .input_split import (InputSplit, InputSplitBase, LineSplitter,
                          RecordIOSplitter, expand_uris)
from .wrappers import ThreadedInputSplit, CachedInputSplit, ShuffleInputSplit
from .remote_filesys import (RangedReadStream, HttpFileSystem, S3FileSystem,
                             GCSFileSystem, WebHDFSFileSystem,
                             AzureFileSystem, sign_v4)
from .indexed_recordio_split import IndexedRecordIOSplit, write_recordio_index
from .single_file_split import SingleFileSplit

__all__ = [
    "URI", "URISpec", "FileInfo", "FileSystem", "LocalFileSystem",
    "FS_REGISTRY", "get_filesystem", "open_stream",
    "open_seek_stream_for_read", "list_directory_recursive",
    "KMAGIC", "RecordIOWriter", "RecordIOReader", "RecordIOChunkReader",
    "encode_lrec", "decode_lrec",
    "InputSplit", "InputSplitBase", "LineSplitter", "RecordIOSplitter",
    "ThreadedInputSplit", "CachedInputSplit", "ShuffleInputSplit",
    "IndexedRecordIOSplit", "SingleFileSplit", "write_recordio_index",
    "create_input_split", "expand_uris",
    "RangedReadStream", "HttpFileSystem", "S3FileSystem", "GCSFileSystem",
    "WebHDFSFileSystem", "AzureFileSystem", "sign_v4",
]


def create_input_split(uri: str, part_index: int = 0, num_parts: int = 1,
                       split_type: str = "text", *, threaded: bool = True,
                       shuffle: bool = False, num_shuffle_parts: int = 16,
                       shuffle_seed: int = 0, index_uri: Optional[str] = None,
                       batch_size: int = 256) -> InputSplit:
    """Create a partitioned record stream (reference ``InputSplit::Create`` `io.h:241`)."""
    spec = URISpec(uri, part_index, num_parts)
    check(num_parts > 0 and 0 <= part_index < num_parts,
          f"bad partition spec {part_index}/{num_parts}")

    if split_type == "stdin" or spec.uri in ("stdin://", "-"):
        return SingleFileSplit(spec.uri)

    if split_type == "indexed_recordio":
        idx = index_uri or spec.args.get("index")
        if idx is None:
            raise DMLCError("indexed_recordio requires index_uri or ?index= arg")
        return IndexedRecordIOSplit(spec.uri, idx, part_index, num_parts,
                                    shuffle=shuffle, seed=shuffle_seed,
                                    batch_size=batch_size)

    def make_base(pi: int, np_: int) -> InputSplitBase:
        if split_type in ("text", "line"):
            return LineSplitter(spec.uri, pi, np_)
        if split_type == "recordio":
            return RecordIOSplitter(spec.uri, pi, np_)
        raise DMLCError(f"unknown InputSplit type {split_type!r}")

    if shuffle:
        base = make_base(part_index * num_shuffle_parts,
                         num_parts * num_shuffle_parts)
        split: InputSplit = ShuffleInputSplit(
            base, part_index, num_parts,
            num_shuffle_parts=num_shuffle_parts, seed=shuffle_seed)
    else:
        split = make_base(part_index, num_parts)

    if spec.cache_file is not None:
        if shuffle:
            raise DMLCError("#cachefile cannot be combined with shuffle "
                            "(the cache wrapper does not repartition; "
                            "reference cached_input_split.h:87)")
        return CachedInputSplit(split, spec.cache_file)
    if threaded:
        return ThreadedInputSplit(split)
    return split
