"""Indexed recordio split: partition by record *count* with optional per-epoch
record shuffling — capability parity with reference
``src/io/indexed_recordio_split.{h,cc}``.

The reference reads an external text index file of ``key offset`` pairs
(`ReadIndexFile` .cc:43-61), partitions the record list evenly by count
(.cc:12-41), batches reads (`NextBatchEx` .cc:158-211) and, when shuffling,
visits records via a seeded mt19937 permutation regenerated every epoch
(`BeforeFirst` .cc:220-232) with a seek per record (.cc:163-190).
"""

from __future__ import annotations

import numpy as np
from typing import List, Optional, Tuple

from ..utils import DMLCError, check
from .filesys import get_filesystem, open_stream
from .input_split import InputSplit, expand_uris
from .recordio import RecordIOReader
from .uri import URI

__all__ = ["IndexedRecordIOSplit", "write_recordio_index"]


def write_recordio_index(rec_uri: str, index_uri: str) -> int:
    """Build a ``key offset`` index file for a recordio file (utility the
    reference assumes exists; format per `indexed_recordio_split.cc:43-61`)."""
    n = 0
    with open_stream(rec_uri, "r") as f, open_stream(index_uri, "w") as out:
        reader = RecordIOReader(f)
        while True:
            offset = f.tell()
            rec = reader.next_record()
            if rec is None:
                break
            out.write(f"{n} {offset}\n".encode())
            n += 1
    return n


class IndexedRecordIOSplit(InputSplit):
    """Record-count partitioning over an indexed recordio file."""

    def __init__(self, uri: str, index_uri: str, part_index: int,
                 num_parts: int, shuffle: bool = False, seed: int = 0,
                 batch_size: int = 256):
        self.uri = uri
        self.files = expand_uris(uri)
        check(len(self.files) == 1,
              "IndexedRecordIOSplit supports a single recordio file per index")
        self._fs = get_filesystem(URI(self.files[0].path))
        self._stream = self._fs.open_for_read(URI(self.files[0].path))
        self.shuffle = shuffle
        self.seed = seed
        self.batch_size = batch_size
        self._epoch = 0
        # index: offsets[i] = byte offset of record i (reference ReadIndexFile)
        offsets: List[Tuple[int, int]] = []
        with open_stream(index_uri, "r") as f:
            for line in f.read().decode().splitlines():
                parts = line.split()
                if not parts:
                    continue
                if len(parts) < 2:
                    raise DMLCError(f"bad index line {line!r}")
                offsets.append((int(parts[0]), int(parts[1])))
        offsets.sort()
        self._offsets = np.array([o for _, o in offsets], dtype=np.int64)
        self.num_records_total = len(self._offsets)
        self.reset_partition(part_index, num_parts)

    def reset_partition(self, part_index: int, num_parts: int) -> None:
        check(0 <= part_index < num_parts,
              f"bad partition {part_index}/{num_parts}")
        # partition by record count (reference .cc:12-41)
        n = self.num_records_total
        step = (n + num_parts - 1) // num_parts
        self._rec_begin = min(step * part_index, n)
        self._rec_end = min(step * (part_index + 1), n)
        self.part_index, self.num_parts = part_index, num_parts
        self._epoch = 0
        self.before_first()

    def before_first(self) -> None:
        self._perm = np.arange(self._rec_begin, self._rec_end, dtype=np.int64)
        if self.shuffle:
            # fresh permutation every epoch (reference .cc:220-232)
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(self._perm)
        self._epoch += 1
        self._pos = 0

    def _read_record_at(self, rec_idx: int) -> bytes:
        self._stream.seek(int(self._offsets[rec_idx]))
        reader = RecordIOReader(self._stream)
        rec = reader.next_record()
        if rec is None:
            raise DMLCError(f"indexed recordio: empty record at index {rec_idx}")
        return rec

    def next_record(self) -> Optional[bytes]:
        if self._pos >= len(self._perm):
            return None
        rec = self._read_record_at(int(self._perm[self._pos]))
        self._pos += 1
        return rec

    def next_batch(self, n: Optional[int] = None) -> Optional[List[bytes]]:
        """Batched read (reference NextBatchEx .cc:158-211)."""
        n = n or self.batch_size
        out: List[bytes] = []
        while len(out) < n:
            rec = self.next_record()
            if rec is None:
                break
            out.append(rec)
        return out or None

    def next_chunk(self) -> Optional[bytes]:
        batch = self.next_batch()
        if batch is None:
            return None
        # re-frame as a plain recordio blob so chunk consumers can parse it
        import io as _io
        from .recordio import RecordIOWriter
        buf = _io.BytesIO()
        w = RecordIOWriter(buf)
        for rec in batch:
            w.write_record(rec)
        return buf.getvalue()

    def close(self) -> None:
        self._stream.close()
