"""Filesystem abstraction with URI-scheme routing — capability parity with
reference ``src/io/filesys.h`` + ``src/io.cc`` + ``src/io/local_filesys.cc``.

Reference design: abstract ``FileSystem`` (GetPathInfo / ListDirectory / Open /
OpenForRead, `filesys.h:75-125`), one singleton per scheme resolved from the
URI protocol (`io.cc:31-60`), BFS ``ListDirectoryRecursive`` (`filesys.cc:9-25`),
and ``Stream::Create`` / ``SeekStream::CreateForRead`` factories
(`io.cc:121-129`).

TPU-native expression: streams are plain Python binary-file-like objects
(``read``/``write``/``seek``/``tell``/``close``) so they interop with numpy,
mmap and the C++ native parsers; schemes register in a
:class:`~dmlc_core_tpu.utils.Registry` so downstream packages can plug in new
stores (GCS/S3/HDFS) exactly like the reference's compile-time gated backends.
"""

from __future__ import annotations

import glob as _glob
import os
import sys
from dataclasses import dataclass
from typing import BinaryIO, List

from ..utils import DMLCError, Registry, check
from .uri import URI

__all__ = [
    "FileInfo", "FileSystem", "LocalFileSystem", "get_filesystem",
    "open_stream", "open_seek_stream_for_read", "list_directory_recursive",
    "FS_REGISTRY",
]

FS_REGISTRY = Registry.get("FileSystem")


@dataclass
class FileInfo:
    """Reference ``FileInfo`` (`filesys.h:63-72`)."""
    path: str
    size: int
    type: str  # 'file' | 'dir'


class FileSystem:
    """Abstract FS (reference `filesys.h:75-125`)."""

    def get_path_info(self, uri: URI) -> FileInfo:
        raise NotImplementedError

    def list_directory(self, uri: URI) -> List[FileInfo]:
        raise NotImplementedError

    def open(self, uri: URI, mode: str) -> BinaryIO:
        """Open a (seekable where possible) binary stream; mode in {'r','w','a'}."""
        raise NotImplementedError

    def open_for_read(self, uri: URI) -> BinaryIO:
        """Open a seekable read stream (reference ``OpenForRead`` `filesys.h:120`)."""
        return self.open(uri, "r")

    def exists(self, uri: URI) -> bool:
        try:
            self.get_path_info(uri)
            return True
        except (DMLCError, OSError):
            return False

    def delete(self, uri: URI) -> None:
        """Remove an object/file.  Net-new vs the reference FS contract
        (`filesys.h:75-125` has no Delete) — object-store checkpoint
        retention needs it; backends without it raise."""
        raise DMLCError(f"delete not supported for scheme "
                        f"{uri.protocol or 'local'!r}")


def list_directory_recursive(fs: FileSystem, uri: URI) -> List[FileInfo]:
    """BFS recursive listing (reference ``ListDirectoryRecursive`` `filesys.cc:9-25`)."""
    out: List[FileInfo] = []
    queue = [uri]
    while queue:
        u = queue.pop(0)
        for info in fs.list_directory(u):
            if info.type == "dir":
                queue.append(URI(info.path))
            else:
                out.append(info)
    return out


class LocalFileSystem(FileSystem):
    """Local files incl. stdin/stdout passthrough (reference `local_filesys.cc`).

    The reference maps the path ``-`` / empty to stdin for read and stdout for
    write (`local_filesys.cc:144-151`).
    """

    def _path(self, uri: URI) -> str:
        return uri.name if uri.protocol else uri.raw

    def get_path_info(self, uri: URI) -> FileInfo:
        path = self._path(uri)
        try:
            st = os.stat(path)
        except OSError as e:
            raise DMLCError(f"LocalFileSystem.get_path_info: {e}") from e
        return FileInfo(path=path, size=st.st_size,
                        type="dir" if os.path.isdir(path) else "file")

    def list_directory(self, uri: URI) -> List[FileInfo]:
        path = self._path(uri)
        try:
            names = sorted(os.listdir(path))
        except OSError as e:
            raise DMLCError(f"LocalFileSystem.list_directory: {e}") from e
        out = []
        for n in names:
            p = os.path.join(path, n)
            st = os.stat(p)
            out.append(FileInfo(path=p, size=st.st_size,
                                type="dir" if os.path.isdir(p) else "file"))
        return out

    def open(self, uri: URI, mode: str) -> BinaryIO:
        path = self._path(uri)
        if path in ("-", ""):
            return sys.stdin.buffer if mode == "r" else sys.stdout.buffer
        check(mode in ("r", "w", "a"), f"bad open mode {mode!r}")
        try:
            return open(path, mode + "b")
        except OSError as e:
            raise DMLCError(f"LocalFileSystem.open({path!r}, {mode!r}): {e}") from e

    def delete(self, uri: URI) -> None:
        try:
            os.unlink(self._path(uri))
        except OSError as e:
            raise DMLCError(f"LocalFileSystem.delete: {e}") from e

    def glob(self, pattern: str) -> List[str]:
        """Wildcard expansion used by InputSplit URI handling
        (reference ``ConvertToURIs`` `input_split_base.cc:96-147`)."""
        return sorted(_glob.glob(pattern))


# scheme registration (reference protocol dispatch `io.cc:31-60`)
_local = LocalFileSystem()
FS_REGISTRY.register("file", description="local filesystem")(lambda: _local)
FS_REGISTRY.register("", description="local filesystem (bare path)")(lambda: _local)


def get_filesystem(uri: URI) -> FileSystem:
    """Resolve the FileSystem for a URI scheme (reference ``GetInstance`` `io.cc:31`)."""
    entry = FS_REGISTRY.find(uri.scheme)
    if entry is None:
        raise DMLCError(
            f"unknown filesystem scheme {uri.scheme!r} in {uri.raw!r}; "
            f"registered: {FS_REGISTRY.list_names()}")
    return entry()


def open_stream(uri_str: str, mode: str) -> BinaryIO:
    """Reference ``Stream::Create`` (`io.cc:121-127`)."""
    uri = URI(uri_str)
    return get_filesystem(uri).open(uri, mode)


def open_seek_stream_for_read(uri_str: str) -> BinaryIO:
    """Reference ``SeekStream::CreateForRead`` (`io.cc:129-133`)."""
    uri = URI(uri_str)
    return get_filesystem(uri).open_for_read(uri)
