"""Automated incident diagnosis: from "a breach fired" to "here is who.

Every surface the telemetry plane grew — wide events, the tiered
timeline, critical-path analytics, the straggler board, fleet consoles —
still required a human to *correlate* them after a page.  On a
disaggregated fleet (dispatcher + workers + routers + replicas, the
tf.data-service shape of arxiv 2210.14826) that correlation is the slow
part of every incident.  This module mechanizes it: given an incident
window (an SLO/burn-rate breach, a flight trigger, or an explicit
``?since=/until=``), four independent analyzers each produce scored
suspects and a merger folds them into one ranked report
(schema ``dmlc.diagnosis/1``):

1. **Wide-event dimension differencing** (BubbleUp-style): split the
   wide-event ring into a *bad* population (errored outcomes, or
   robustly-slow ``dur_ms``, inside the window) and a baseline (all
   other buffered events) and rank every dimension value by how much
   more often it appears among the bad — "all slow requests carry
   ``replica=host:7013``" surfaces as the top row, no grouping query
   written by hand.
2. **Timeline lead/lag correlation**: scan every
   :class:`~dmlc_core_tpu.telemetry.timeseries.HistoryStore` series for
   its deviation onset (EWMA + MAD robust z, the
   :class:`~dmlc_core_tpu.telemetry.anomaly.StreamingStat` machinery)
   and rank series that deviated *before* the breached series by
   lead time × deviation magnitude — the upstream cause usually moves
   first.
3. **Critical-path regression diff**: re-run
   :func:`~dmlc_core_tpu.telemetry.critical_path.analyze` over the
   breach-window span records and over a pre-incident baseline window,
   and rank spans whose share of critical-path self time *grew*.
4. **Fleet attribution**: fold the tracker's
   :class:`~dmlc_core_tpu.telemetry.anomaly.StragglerBoard` and the
   per-worker/replica rows of a merged ``/fleet`` doc into entity
   suspects, corroborated against the wide-event verdict when both name
   the same replica/worker.

Served at ``/diagnose`` on every exporter (the tracker / data-service
dispatcher / fleet registry wire their *merged* stores in), attached to
every flight bundle as ``diagnosis.json`` + ``diagnosis.txt``, and
auto-triggered by :class:`~dmlc_core_tpu.telemetry.slo.BurnRateMonitor`
breaches (``DMLC_DIAGNOSE_ON_BREACH=0`` opts out) so the bundle of the
page that woke you already contains the ranked verdict.

Knobs: ``DMLC_DIAGNOSE`` (master gate for the automatic paths, default
1), ``DMLC_DIAGNOSE_WINDOW`` (incident window seconds when no breach /
explicit window scopes it, default 60), ``DMLC_DIAGNOSE_BASELINE``
(pre-incident baseline seconds, default 300), ``DMLC_DIAGNOSE_TOP``
(suspects kept per analyzer and overall, default 5),
``DMLC_DIAGNOSE_SLOW_MS`` (wide-event slow threshold; 0 = adaptive
median + 4·MAD).  Accounting: ``telemetry.diagnose.runs`` /
``telemetry.diagnose.wall_ms`` / ``telemetry.diagnose.suspects``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.metrics import metrics
from ..utils.parameter import get_env
from . import critical_path as _critical_path
from . import timeseries as _timeseries
from . import trace as _trace
from . import wide_events as _wide
from .anomaly import StreamingStat, _median

__all__ = ["DiagnosisEngine", "diagnose", "render_text", "on_breach",
           "incident_diagnosis", "default_engine", "DIAGNOSIS_SCHEMA"]

DIAGNOSIS_SCHEMA = "dmlc.diagnosis/1"

#: wide-event fields that are *measures* (continuous magnitudes) — they
#: feed the slowness classifier, not the dimension differencer.  The
#: ``diagnosis-vocabulary`` lint rule checks every name here against
#: ``wide_events.FIELDS``.
MEASURE_FIELDS = frozenset({
    "dur_ms", "queue_ms", "rows", "nnz", "batch_rows", "batch_nnz",
    "bytes", "frames",
})

#: per-event-unique identity fields — differencing them would ring the
#: cardinality alarm BubbleUp exists to avoid (also lint-checked).
IDENTITY_FIELDS = frozenset({"seq", "ts", "trace_id", "req_id"})

#: the entity-valued fields fleet attribution corroborates against
#: (lint-checked like the sets above).
ENTITY_FIELDS = frozenset({"replica", "worker"})

#: the metric names this module owns — one row each in the
#: docs/observability.md catalog (the lint rule checks the mirror).
DIAG_METRICS = ("telemetry.diagnose.runs", "telemetry.diagnose.wall_ms",
                "telemetry.diagnose.suspects")

#: series whose movement is an *effect* of the breach machinery itself —
#: never lead/lag suspects
_SELF_SERIES_PREFIXES = ("slo.", "telemetry.diagnose", "flight.",
                        "telemetry.timeline", "anomaly.")

#: robust-z threshold for a series' deviation onset, and the minimum
#: baseline points before a z is trusted
_ONSET_Z = 3.0
_ONSET_MIN_N = 5
_Z_CAP = 1e3


def event_field(ev: Dict[str, Any], name: str) -> Any:
    """The one sanctioned spelling for reading a wide-event field inside
    the analyzers — the ``diagnosis-vocabulary`` lint rule keys on this
    call name to verify every referenced field is in ``FIELDS``."""
    return ev.get(name)


def _robust_slow_ms(durs: List[float]) -> float:
    """Adaptive slow threshold: median + 4·(1.4826·MAD), floored at half
    the median — a bimodal window (one slow replica among healthy ones)
    puts the threshold between the modes; an all-healthy window keeps
    ordinary jitter below it."""
    med = _median(durs)
    mad = _median([abs(d - med) for d in durs])
    return med + max(4.0 * 1.4826 * mad, 0.5 * med, 1e-3)


class DiagnosisEngine:
    """Four analyzers + the merger over injectable evidence sources.

    Every source is a zero-arg callable so the same engine serves a
    process-local exporter (defaults: the global wide-event ring, the
    global history store, the global span recorder) or a control plane's
    *merged* fleet view (the tracker injects its fleet history store and
    straggler board; dispatcher/registry inject theirs).  Tests inject
    synthetic populations and a synthetic clock.
    """

    def __init__(self, *,
                 events_fn: Optional[Callable[[], List[Dict[str, Any]]]]
                 = None,
                 history: Optional["_timeseries.HistoryStore"] = None,
                 records_fn: Optional[Callable[[], List[Dict[str, Any]]]]
                 = None,
                 stragglers_fn: Optional[Callable[[], Dict[str, Any]]]
                 = None,
                 fleet_fn: Optional[Callable[[], Dict[str, Any]]] = None
                 ) -> None:
        self._events_fn = events_fn or (lambda: _wide.wide_log.snapshot())
        self._history = history
        self._records_fn = records_fn or _trace.recorder.snapshot
        self._stragglers_fn = stragglers_fn
        self._fleet_fn = fleet_fn

    @property
    def history(self) -> "_timeseries.HistoryStore":
        return self._history if self._history is not None \
            else _timeseries.history

    # -- analyzer 1: wide-event dimension differencing -------------------
    def _diff_wide_events(self, since: float, until: float, top: int,
                          slow_ms: float) -> Dict[str, Any]:
        events = self._events_fn()
        in_window = [e for e in events
                     if since <= float(event_field(e, "ts") or 0) <= until]
        durs = [float(event_field(e, "dur_ms"))
                for e in in_window
                if isinstance(event_field(e, "dur_ms"), (int, float))]
        if slow_ms <= 0:
            slow_ms = _robust_slow_ms(durs) if durs else float("inf")

        def _is_bad(e: Dict[str, Any]) -> bool:
            outcome = event_field(e, "outcome")
            if outcome is not None and str(outcome).upper() != "OK":
                return True
            d = event_field(e, "dur_ms")
            return isinstance(d, (int, float)) and float(d) > slow_ms

        bad = [e for e in in_window if _is_bad(e)]
        bad_ids = {id(e) for e in bad}
        base = [e for e in events if id(e) not in bad_ids]
        doc: Dict[str, Any] = {
            "events": len(events), "in_window": len(in_window),
            "bad": len(bad), "baseline": len(base),
            "slow_ms": None if slow_ms == float("inf")
            else round(slow_ms, 3),
            "suspects": [],
        }
        if not bad or not base:
            return doc
        dims = _wide.FIELDS - MEASURE_FIELDS - IDENTITY_FIELDS

        def _counts(pop: List[Dict[str, Any]]
                    ) -> Dict[Tuple[str, str], int]:
            out: Dict[Tuple[str, str], int] = {}
            for e in pop:
                for f in dims:
                    v = e.get(f)
                    if v is not None:
                        key = (f, str(v))
                        out[key] = out.get(key, 0) + 1
            return out

        bad_counts = _counts(bad)
        base_counts = _counts(base)
        nb, nz = len(bad), len(base)
        suspects = []
        for (f, v), cb in bad_counts.items():
            if cb < min(2, nb):
                continue            # one stray event is not a pattern
            p_bad = cb / nb
            p_base = base_counts.get((f, v), 0) / nz
            score = (p_bad - p_base) * p_bad
            if score <= 0:
                continue
            suspects.append({"field": f, "value": v,
                             "bad": cb, "bad_frac": round(p_bad, 4),
                             "base_frac": round(p_base, 4),
                             "score": round(score, 6)})
        suspects.sort(key=lambda s: (-s["score"], s["field"], s["value"]))
        doc["suspects"] = suspects[:top]
        return doc

    # -- analyzer 2: timeline lead/lag correlation -----------------------
    @staticmethod
    def _onset(pts: List[Tuple[float, float]]
               ) -> Tuple[Optional[float], float]:
        """First timestamp where a series leaves its own EWMA+MAD band
        (``(onset_ts, max_abs_z)``); ``(None, 0)`` when it never does.
        The estimate is frozen at onset so the magnitude is measured
        against the pre-deviation baseline, not a corrupted one."""
        stat = StreamingStat(alpha=0.25)
        onset: Optional[float] = None
        mag = 0.0
        for ts, v in pts:
            z = stat.zscore(v, rel_floor=0.25)
            if onset is None:
                if stat.n >= _ONSET_MIN_N and abs(z) > _ONSET_Z:
                    onset = ts
                    mag = abs(z)
                else:
                    stat.update(v)
            else:
                mag = max(mag, abs(z))
        return onset, min(mag, _Z_CAP)

    def _correlate_timeline(self, since: float, until: float, top: int,
                            breach_series: Optional[str]
                            ) -> Dict[str, Any]:
        history = self.history
        baseline_s = float(get_env("DMLC_DIAGNOSE_BASELINE", 300.0))
        span = (until - since) + baseline_s
        ref_onset = since
        if breach_series:
            pts = [(ts, v) for ts, v in history.query(
                breach_series, since=span, now=until) if ts <= until]
            onset, _mag = self._onset(pts)
            if onset is not None:
                ref_onset = onset
        doc: Dict[str, Any] = {"breach_series": breach_series,
                               "breach_onset": round(ref_onset, 3),
                               "series_scanned": 0, "suspects": []}
        step = history.tiers[0][0] if history.tiers else 1.0
        suspects = []
        for name in history.series_names():
            if name == breach_series or \
                    name.startswith(_SELF_SERIES_PREFIXES):
                continue
            pts = [(ts, v) for ts, v in history.query(
                name, since=span, now=until) if ts <= until]
            doc["series_scanned"] += 1
            onset, mag = self._onset(pts)
            # leaders only: a series that moved after the breach is an
            # effect, not a cause (step of slack absorbs sampler phase)
            if onset is None or onset > ref_onset + step:
                continue
            lead_s = max(0.0, ref_onset - onset)
            suspects.append({"series": name,
                             "onset": round(onset, 3),
                             "lead_s": round(lead_s, 3),
                             "magnitude": round(mag, 3),
                             "score": round((lead_s + step) * mag, 6)})
        suspects.sort(key=lambda s: (-s["score"], s["series"]))
        doc["suspects"] = suspects[:top]
        return doc

    # -- analyzer 3: critical-path regression diff -----------------------
    def _diff_critical_path(self, since: float, until: float, top: int
                            ) -> Dict[str, Any]:
        baseline_s = float(get_env("DMLC_DIAGNOSE_BASELINE", 300.0))
        base_start = since - baseline_s
        records = [r for r in self._records_fn()
                   if r.get("kind") == "span"]

        def _end_s(r: Dict[str, Any]) -> float:
            return (float(r.get("ts_us", 0))
                    + float(r.get("dur_us", 0))) / 1e6

        inc = [r for r in records if since <= _end_s(r) <= until]
        base = [r for r in records if base_start <= _end_s(r) < since]
        doc: Dict[str, Any] = {"incident_spans": len(inc),
                               "baseline_spans": len(base), "suspects": []}
        if not inc:
            return doc

        def _shares(recs: List[Dict[str, Any]]) -> Dict[str, float]:
            st = _critical_path.analyze(top=max(top, 10),
                                        records=recs)["self_time_us"]
            total = sum(st.values()) or 1
            return {k: v / total for k, v in st.items()}

        inc_sh = _shares(inc)
        base_sh = _shares(base) if base else {}
        suspects = []
        for name, share in inc_sh.items():
            growth = share - base_sh.get(name, 0.0)
            if growth <= 0:
                continue
            suspects.append({"span": name,
                             "share_incident": round(share, 4),
                             "share_baseline": round(
                                 base_sh.get(name, 0.0), 4),
                             "score": round(growth, 6)})
        suspects.sort(key=lambda s: (-s["score"], s["span"]))
        doc["suspects"] = suspects[:top]
        doc["baseline_missing"] = not base
        return doc

    # -- analyzer 4: fleet attribution -----------------------------------
    def _attribute_fleet(self, top: int) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"sources": [], "suspects": []}
        suspects: List[Dict[str, Any]] = []
        if self._stragglers_fn is not None:
            try:
                snap = self._stragglers_fn() or {}
                doc["sources"].append("stragglers")
                worst: Dict[str, float] = {}
                for per_rank in (snap.get("stages") or {}).values():
                    for rank, d in per_rank.items():
                        if d.get("straggler"):
                            worst[rank] = max(worst.get(rank, 0.0),
                                              float(d.get("z", 0.0)))
                for rank, z in worst.items():
                    suspects.append({"entity": "rank", "id": str(rank),
                                     "reason": "straggler",
                                     "score": round(min(z, _Z_CAP), 3)})
            except Exception as e:
                doc["stragglers_error"] = str(e)
        if self._fleet_fn is not None:
            try:
                fleet = self._fleet_fn() or {}
                doc["sources"].append("fleet")
                for kind in ("replicas", "workers"):
                    for key, row in (fleet.get(kind) or {}).items():
                        if not isinstance(row, dict):
                            continue
                        entity = kind[:-1]
                        # wide events carry host:port addrs while fleet
                        # rows key on jobids — keep both spellings so
                        # corroboration matches either
                        ident = {"entity": entity, "id": str(key),
                                 "addr": str(row.get("addr") or "")}
                        if not row.get("alive", True):
                            suspects.append({**ident, "reason": "dead",
                                             "score": 10.0})
                        elif row.get("straggler"):
                            suspects.append({**ident,
                                             "reason": "straggler",
                                             "score": 6.0})
                        elif str(row.get("health", "ok")) not in (
                                "ok", "?"):
                            suspects.append({
                                **ident,
                                "reason": str(row.get("health")),
                                "score": 4.0 + float(
                                    row.get("queue_fraction", 0.0))})
            except Exception as e:
                doc["fleet_error"] = str(e)
        suspects.sort(key=lambda s: (-s["score"], s["entity"], s["id"]))
        doc["suspects"] = suspects[:top]
        return doc

    # -- the merger ------------------------------------------------------
    @staticmethod
    def _merge(analyzers: Dict[str, Dict[str, Any]], top: int
               ) -> List[Dict[str, Any]]:
        """Normalize each analyzer's scores to [0, 1] (its top suspect
        scores 1.0) and rank the union; a fleet entity also named by the
        wide-event differ is corroborated and boosted — two independent
        analyzers agreeing beats either one alone."""
        entity_values = {s["value"]
                         for s in analyzers["wide_events"]["suspects"]
                         if s["field"] in ENTITY_FIELDS}
        merged: List[Dict[str, Any]] = []
        subjects = {"wide_events": lambda s: f"{s['field']}={s['value']}",
                    "timeline": lambda s: s["series"],
                    "critical_path": lambda s: f"span {s['span']}",
                    "fleet": lambda s: f"{s['entity']} {s['id']}"}
        for name, doc in analyzers.items():
            sus = doc.get("suspects") or []
            if not sus:
                continue
            peak = max(float(s["score"]) for s in sus) or 1.0
            for s in sus:
                entry = {"analyzer": name,
                         "subject": subjects[name](s),
                         "score": round(float(s["score"]) / peak, 4),
                         "detail": {k: v for k, v in s.items()
                                    if k != "score"}}
                if name == "fleet" and (
                        s["id"] in entity_values
                        or s.get("addr") in entity_values):
                    entry["corroborated"] = True
                    entry["score"] = round(min(1.0, entry["score"] + 0.25),
                                           4)
                merged.append(entry)
        merged.sort(key=lambda e: (-e["score"], e["analyzer"],
                                   e["subject"]))
        out = merged[:top]
        for i, e in enumerate(out, start=1):
            e["rank"] = i
        return out

    # -- entry points ----------------------------------------------------
    def run(self, since: Optional[float] = None,
            until: Optional[float] = None,
            top: Optional[int] = None,
            breach: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """One diagnosis pass → the ``dmlc.diagnosis/1`` document.
        ``since``/``until`` are unix timestamps bounding the incident
        window; a ``breach`` dict (a burn/SLO firing) scopes the window
        and names the reference series when ``since`` is not given."""
        t0 = time.perf_counter()
        if until is None:
            until = time.time()
        if top is None:
            top = max(1, int(get_env("DMLC_DIAGNOSE_TOP", 5)))
        if since is None:
            window = float(get_env("DMLC_DIAGNOSE_WINDOW", 60.0))
            if breach and breach.get("window_s"):
                window = float(breach["window_s"])
            since = until - window
        breach_series = (breach or {}).get("series")
        slow_ms = float(get_env("DMLC_DIAGNOSE_SLOW_MS", 0.0))
        analyzers = {
            "wide_events": self._diff_wide_events(since, until, top,
                                                  slow_ms),
            "timeline": self._correlate_timeline(since, until, top,
                                                 breach_series),
            "critical_path": self._diff_critical_path(since, until, top),
            "fleet": self._attribute_fleet(top),
        }
        suspects = self._merge(analyzers, top)
        wall_ms = (time.perf_counter() - t0) * 1e3
        metrics.counter("telemetry.diagnose.runs").add(1)
        metrics.histogram("telemetry.diagnose.wall_ms").observe(wall_ms)
        metrics.gauge("telemetry.diagnose.suspects").set(len(suspects))
        return {
            "schema": DIAGNOSIS_SCHEMA,
            "ts": time.time(),
            "window": {"since": since, "until": until,
                       "baseline_s": float(
                           get_env("DMLC_DIAGNOSE_BASELINE", 300.0))},
            "trigger": ({"kind": "breach", "breach": breach}
                        if breach else {"kind": "explicit"}),
            "analyzers": analyzers,
            "suspects": suspects,
            "wall_ms": round(wall_ms, 3),
        }

    def endpoint_doc(self, since_s: Optional[float] = None,
                     until_s: Optional[float] = None,
                     top: Optional[int] = None) -> Dict[str, Any]:
        """``GET /diagnose`` body.  ``since_s``/``until_s`` are seconds
        back from now; with neither given, a recent breach (if any)
        scopes the window so a bare ``/diagnose`` after a page answers
        about *that* incident."""
        now = time.time()
        until = now - float(until_s) if until_s else now
        since = until - float(since_s) if since_s else None
        breach = _recent_breach() if since is None else None
        return self.run(since=since, until=until, top=top, breach=breach)


# ---------------------------------------------------------------------------
# process-global engine + breach auto-trigger
# ---------------------------------------------------------------------------

_engine_lock = threading.Lock()
_default_engine: Optional[DiagnosisEngine] = None

#: (breach dict, unix ts) of the most recent burn/SLO firing, and the
#: diagnosis it triggered — what bare ``/diagnose`` hits and flight
#: bundles attach
_last_breach: Optional[Tuple[Dict[str, Any], float]] = None
_last_doc: Optional[Dict[str, Any]] = None


def default_engine() -> DiagnosisEngine:
    """The process-global engine over the global ring/store/recorder."""
    global _default_engine
    with _engine_lock:
        if _default_engine is None:
            _default_engine = DiagnosisEngine()
        return _default_engine


def diagnose(since: Optional[float] = None, until: Optional[float] = None,
             top: Optional[int] = None,
             breach: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One diagnosis pass on the process-global engine."""
    return default_engine().run(since=since, until=until, top=top,
                                breach=breach)


def _recent_breach() -> Optional[Dict[str, Any]]:
    """The last recorded breach, while it is still fresher than twice
    its own window (after that a bare /diagnose means "now", not "then")."""
    got = _last_breach
    if got is None:
        return None
    breach, ts = got
    horizon = 2.0 * float(breach.get("window_s")
                          or get_env("DMLC_DIAGNOSE_WINDOW", 60.0))
    return breach if time.time() - ts <= horizon else None


def on_breach(breach: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The SLO-monitor hook: record the breach and run a breach-scoped
    diagnosis (``DMLC_DIAGNOSE=0`` / ``DMLC_DIAGNOSE_ON_BREACH=0`` opt
    out) so the flight bundle dumped moments later carries the verdict."""
    global _last_breach, _last_doc
    if not get_env("DMLC_DIAGNOSE", True) \
            or not get_env("DMLC_DIAGNOSE_ON_BREACH", True):
        return None
    _last_breach = (dict(breach), time.time())
    _last_doc = default_engine().run(breach=breach)
    return _last_doc


def incident_diagnosis() -> Optional[Dict[str, Any]]:
    """The flight-recorder hook: the breach-scoped diagnosis when one is
    fresh, else a fresh default-window run.  ``DMLC_DIAGNOSE=0`` opts
    the bundle section out entirely (None → no file)."""
    if not get_env("DMLC_DIAGNOSE", True):
        return None
    breach = _recent_breach()
    if breach is not None and _last_doc is not None:
        return _last_doc
    return default_engine().run(breach=breach)


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render_text(doc: Dict[str, Any]) -> str:
    """``diagnosis.txt`` / ``/diagnose?format=text``: the merged ranking
    first (the headline), then each analyzer's own table."""
    w = doc.get("window", {})
    lines = [f"diagnosis @ {doc.get('ts', 0):.0f} "
             f"window={w.get('since', 0):.0f}..{w.get('until', 0):.0f} "
             f"({doc.get('wall_ms', 0):.1f} ms)"]
    trig = doc.get("trigger", {})
    if trig.get("kind") == "breach":
        b = trig.get("breach") or {}
        lines.append(f"trigger: breach {b.get('rule', '?')} "
                     f"severity={b.get('severity', '-')}")
    sus = doc.get("suspects") or []
    lines.append("ranked suspects:" if sus
                 else "ranked suspects: (none — quiet window)")
    for s in sus:
        flag = " [corroborated]" if s.get("corroborated") else ""
        lines.append(f"  #{s['rank']} [{s['analyzer']}] {s['subject']} "
                     f"score={s['score']:.3f}{flag}")
    az = doc.get("analyzers", {})
    we = az.get("wide_events", {})
    lines.append(f"wide events: {we.get('bad', 0)} bad / "
                 f"{we.get('baseline', 0)} baseline "
                 f"(slow>{we.get('slow_ms', '-')}ms)")
    for s in we.get("suspects") or []:
        lines.append(f"  {s['field']}={s['value']}  "
                     f"bad {s['bad_frac'] * 100:.0f}% vs base "
                     f"{s['base_frac'] * 100:.0f}%")
    tl = az.get("timeline", {})
    lines.append(f"timeline: {tl.get('series_scanned', 0)} series vs "
                 f"{tl.get('breach_series') or '(window start)'}")
    for s in tl.get("suspects") or []:
        lines.append(f"  {s['series']}  lead={s['lead_s']:.1f}s "
                     f"|z|={s['magnitude']:.1f}")
    cp = az.get("critical_path", {})
    lines.append(f"critical path: {cp.get('incident_spans', 0)} incident "
                 f"vs {cp.get('baseline_spans', 0)} baseline span(s)")
    for s in cp.get("suspects") or []:
        lines.append(f"  {s['span']}  share "
                     f"{s['share_baseline'] * 100:.1f}% -> "
                     f"{s['share_incident'] * 100:.1f}%")
    fl = az.get("fleet", {})
    if fl.get("sources"):
        lines.append(f"fleet ({'+'.join(fl['sources'])}):")
        for s in fl.get("suspects") or []:
            lines.append(f"  {s['entity']} {s['id']}  {s['reason']} "
                         f"score={s['score']:.1f}")
    return "\n".join(lines) + "\n"
