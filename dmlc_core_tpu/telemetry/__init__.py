"""Cluster-wide telemetry plane: trace propagation, Chrome/Perfetto
export, Prometheus exposition, and tracker-side aggregation.

Layers (see ``docs/observability.md``):

* :mod:`telemetry.trace` — ``TraceContext`` / ``span()`` propagation and
  the process-global span ring buffer.
* :mod:`telemetry.sampling` — tail-based trace sampling: buffer whole
  traces, keep errors/slow/SLO-breach/debug plus a consistent-hash
  floor, coordination-free across tiers.
* :mod:`telemetry.wide_events` — one canonical wide event per serving
  request / data-service lease, served at ``/events``.
* :mod:`telemetry.chrome_trace` — export recorded spans as Chrome
  trace-event JSON (open in Perfetto).
* :mod:`telemetry.exposition` — Prometheus text rendering and the
  ``/metrics`` / ``/healthz`` / ``/spans`` HTTP exporter.
* :mod:`telemetry.aggregate` — merge rank-tagged registry states into
  the tracker's fleet view.
* :mod:`telemetry.flight` — always-on flight recorder dumping incident
  bundles on fatal paths, injected faults, SLO breaches, or ``/flight``.
* :mod:`telemetry.anomaly` — streaming stall/straggler detection and the
  declarative ``DMLC_SLO_SPEC`` rule monitor.
* :mod:`telemetry.profiling` — stdlib sampling stack profiler behind
  ``/profile`` and the flight recorder's incident attachment, plus
  collapsed-stack profile diffing (``/profile?diff=1``).
* :mod:`telemetry.diagnose` — automated incident diagnosis: wide-event
  differencing, timeline lead/lag correlation, critical-path regression
  diff and fleet attribution merged into one ranked suspect report at
  ``/diagnose`` (auto-attached to flight bundles on breaches).
* :mod:`telemetry.xla_introspect` — jit retrace watchdog and device
  memory gauges.

Everything here is stdlib-only on top of ``utils.metrics`` — safe to
import in any process, including JAX-less tooling (the XLA sampler is a
guarded no-op without JAX).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .aggregate import merge_states, render_fleet, state_to_snapshot
from .anomaly import (SloMonitor, SloRule, SloSpecError, StallDetector,
                      StragglerBoard, StreamingStat, maybe_monitor_from_env,
                      parse_slo_spec)
from .chrome_trace import to_chrome_trace, write_chrome_trace
# NOTE: the submodule's convenience function is exported as
# run_diagnosis — binding the package attribute ``diagnose`` to a
# function would shadow the *module* for every ``from . import
# diagnose`` site (exposition/flight/anomaly lazy imports)
from .diagnose import DIAGNOSIS_SCHEMA, DiagnosisEngine, incident_diagnosis
from .diagnose import diagnose as run_diagnosis
from .diagnose import render_text as render_diagnosis
from .exposition import (TelemetryServer, maybe_start_from_env,
                         render_openmetrics, render_prometheus,
                         render_series)
from .flight import (FlightRecorder, dump_incident, flight_recorder,
                     maybe_arm_from_env, register_contributor,
                     unregister_contributor)
from .profiling import (SamplingProfiler, diff_collapsed, incident_profile,
                        profile_for)
from .sampling import (TailSampler, TraceBuffer, debug_trace_id, hash_keep,
                       is_debug, mark_debug, maybe_install_from_env,
                       was_kept)
from .sampling import install as install_sampler
from .sampling import uninstall as uninstall_sampler
from .trace import (Span, SpanRecorder, TraceContext, activate, add_event,
                    current, current_trace_id, format_id, new_trace_id,
                    recorder, span, start_span)
from .wide_events import FIELDS as WIDE_EVENT_FIELDS
from .wide_events import WideEventLog, events_doc, wide_event, wide_log
from .xla_introspect import RetraceWatchdog, sample_memory, watchdog

__all__ = [
    "TraceContext", "Span", "SpanRecorder", "recorder", "span",
    "start_span", "activate", "add_event", "current", "current_trace_id",
    "new_trace_id", "format_id",
    "to_chrome_trace", "write_chrome_trace",
    "render_prometheus", "render_series", "render_openmetrics",
    "TelemetryServer", "maybe_start_from_env",
    "TailSampler", "TraceBuffer", "hash_keep", "is_debug", "mark_debug",
    "debug_trace_id", "was_kept", "maybe_install_from_env",
    "install_sampler", "uninstall_sampler",
    "WideEventLog", "wide_log", "wide_event", "events_doc",
    "WIDE_EVENT_FIELDS",
    "merge_states", "state_to_snapshot", "render_fleet",
    "dump_artifacts",
    "FlightRecorder", "flight_recorder", "dump_incident",
    "maybe_arm_from_env", "register_contributor", "unregister_contributor",
    "SamplingProfiler", "profile_for", "incident_profile",
    "diff_collapsed",
    "DiagnosisEngine", "run_diagnosis", "incident_diagnosis",
    "render_diagnosis", "DIAGNOSIS_SCHEMA",
    "StreamingStat", "StallDetector", "StragglerBoard",
    "SloRule", "SloMonitor", "SloSpecError", "parse_slo_spec",
    "maybe_monitor_from_env",
    "RetraceWatchdog", "watchdog", "sample_memory",
]


def dump_artifacts(prefix: str, registry=None) -> dict:
    """Benchmark-exit hook (``--telemetry-out``): write
    ``<prefix>.metrics.json`` (registry snapshot) and
    ``<prefix>.trace.json`` (Chrome trace of recorded spans).
    Returns ``{"metrics": path, "trace": path}``."""
    if registry is None:
        from ..utils.metrics import metrics as registry   # type: ignore
    metrics_path = f"{prefix}.metrics.json"
    tmp = f"{metrics_path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"snapshot": registry.snapshot()}, f, indent=2,
                  sort_keys=True, default=str)
    os.replace(tmp, metrics_path)
    trace_path = write_chrome_trace(f"{prefix}.trace.json")
    return {"metrics": metrics_path, "trace": trace_path}
