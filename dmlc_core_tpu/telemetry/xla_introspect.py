"""XLA introspection: retrace watchdog + device-memory gauges.

The serving engine's whole performance story is the **no-retrace
ladder**: every request shape is bucketed up to an ahead-of-time
compiled executable, so steady-state traffic never touches the XLA
compiler.  That property is invisible until it breaks — a new shape
falls off the ladder, a checkpoint hot-reload silently changes a
signature, a dtype drifts — and then p99 jumps by a compile (seconds,
not microseconds) with nothing in the metrics naming the culprit.

:class:`RetraceWatchdog` makes the property observable:

* every compile is counted per shape bucket with its wall time
  (``xla.compiles``, ``xla.compile_seconds``, ``xla.compile.<bucket>``);
* cache hits are counted so the miss *ratio* is computable;
* once a bucket is **steady** (warmed up / first compile done), any
  further compile for it raises a retrace alert — that is exactly the
  "requests fell off the no-retrace ladder" condition;
* ladder misses (requests too large for any bucket) are counted and
  noted, since they are the adjacent failure mode with the same
  operator response (extend the ladder).

Alerts bump ``xla.retrace_alerts``, latch the ``xla.retrace_alert``
gauge, and leave a note in the flight recorder (via ``sys.modules`` —
this module never imports ``flight``).

:func:`sample_memory` publishes live-buffer and per-device memory
gauges on whatever cadence the caller already has (the rabit telemetry
push, the SLO monitor tick).  It is a guarded no-op without JAX, and
tolerates backends that do not implement ``memory_stats`` (CPU).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, Optional

from ..utils.logging import log_warning
from ..utils.metrics import MetricsRegistry, metrics

__all__ = ["RetraceWatchdog", "watchdog", "sample_memory"]


def _flight_mod():
    return sys.modules.get("dmlc_core_tpu.telemetry.flight")


class RetraceWatchdog:
    """Compile/retrace accounting per shape bucket (see module doc)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self._reg = registry if registry is not None else metrics
        self._lock = threading.Lock()
        # bucket -> {"compiles": n, "wall_s": total, "steady": bool}
        self._buckets: Dict[str, Dict[str, Any]] = {}
        self._alerted = False

    def _bucket(self, key: str) -> Dict[str, Any]:
        b = self._buckets.get(key)
        if b is None:
            b = {"compiles": 0, "wall_s": 0.0, "steady": False}
            self._buckets[key] = b
        return b

    # -- feed points (engine calls these) --------------------------------
    def note_compile(self, bucket: str, wall_s: float) -> bool:
        """A compile happened for ``bucket``; returns True when it was a
        retrace (compile after the bucket went steady) — the alert."""
        retrace = False
        with self._lock:
            b = self._bucket(bucket)
            b["compiles"] += 1
            b["wall_s"] += wall_s
            retrace = b["steady"]
            if retrace:
                self._alerted = True
        self._reg.counter("xla.compiles").add(1)
        self._reg.counter(f"xla.compile.{bucket}").add(1)
        self._reg.histogram("xla.compile_seconds").observe(wall_s)
        if retrace:
            self._reg.counter("xla.retrace_alerts").add(1)
            self._reg.gauge("xla.retrace_alert").set(1)
            log_warning("retrace alert: bucket %s recompiled after steady "
                        "state (%.3fs) — requests fell off the no-retrace "
                        "ladder", bucket, wall_s)
            fl = _flight_mod()
            if fl is not None:
                fl.flight_recorder.note("retrace_alert", bucket=bucket,
                                        wall_s=wall_s)
                fl.dump_incident("retrace_alert", registry=self._reg,
                                 bucket=bucket, wall_s=wall_s)
        return retrace

    def note_hit(self, bucket: str) -> None:
        """A request was served from the compiled cache."""
        self._reg.counter("xla.cache_hits").add(1)
        with self._lock:
            # first hit proves the executable exists → the bucket is
            # steady even if warmup was skipped
            self._bucket(bucket)["steady"] = True

    def note_ladder_miss(self, detail: str = "") -> None:
        """A request did not fit any bucket (``RequestTooLarge``)."""
        self._reg.counter("xla.ladder_misses").add(1)
        self._reg.gauge("xla.retrace_alert").set(1)
        with self._lock:
            self._alerted = True
        fl = _flight_mod()
        if fl is not None:
            fl.flight_recorder.note("ladder_miss", detail=detail)

    def mark_steady(self, bucket: Optional[str] = None) -> None:
        """Declare bucket(s) warmed: compiles from here on are retraces.
        ``warmup_all`` calls this with no argument after the sweep."""
        with self._lock:
            if bucket is None:
                for b in self._buckets.values():
                    b["steady"] = True
            else:
                self._bucket(bucket)["steady"] = True

    def begin_warmup(self) -> None:
        """Open a declared compile window: a fresh engine (checkpoint
        hot-reload, a second replica in-process) recompiles every bucket,
        and those compiles are expected, not retraces."""
        with self._lock:
            for b in self._buckets.values():
                b["steady"] = False

    # -- reading ---------------------------------------------------------
    @property
    def alerted(self) -> bool:
        with self._lock:
            return self._alerted

    def reset_alert(self) -> None:
        with self._lock:
            self._alerted = False
        self._reg.gauge("xla.retrace_alert").set(0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"alerted": self._alerted,
                    "buckets": {k: dict(v)
                                for k, v in self._buckets.items()}}


#: process-global watchdog (the serving engine feeds it)
watchdog = RetraceWatchdog()

_mem_warned = False


def sample_memory(registry: Optional[MetricsRegistry] = None) -> bool:
    """Publish ``xla.live_buffers`` and per-device ``xla.mem.<id>.*``
    gauges; returns False (and stays silent) when JAX is absent.  Safe
    to call on any cadence — it reads runtime counters, it does not walk
    the heap."""
    global _mem_warned
    reg = registry if registry is not None else metrics
    try:
        import jax
    except Exception:
        return False
    try:
        reg.gauge("xla.live_buffers").set(len(jax.live_arrays()))
    except Exception as e:     # pragma: no cover - version drift
        if not _mem_warned:
            _mem_warned = True
            log_warning("xla live-buffer sampling unavailable: %s", e)
    try:
        devices = jax.local_devices()
    except Exception:
        return True
    for dev in devices:
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None           # CPU backend: not implemented
        if not stats:
            continue
        did = getattr(dev, "id", 0)
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                reg.gauge(f"xla.mem.{did}.{key}").set(stats[key])
    reg.gauge("xla.mem.sampled_ts").set(time.time())
    return True
