"""Telemetry time machine: bounded tiered history of any registry.

Every surface PR 3–13 built is point-in-time: ``/metrics`` is *now*,
``/fleet`` is the latest heartbeat, a flight bundle freezes the moment
of the trigger.  The ROADMAP's next moves (backlog-trend autoscaling,
a denoised autotuner objective, burn-rate SLOs) all need *history* —
this module is that substrate, all stdlib, bounded by construction.

:class:`HistoryStore` samples any snapshot source (a
``MetricsRegistry``, or the tracker/dispatcher's merged fleet view) on
a fixed cadence into per-series rings with **tiered downsampling**:
tier 0 keeps every sample (default 1 s × 5 min), higher tiers keep
bucket means (default 10 s × 1 h), so the memory bound is
``series × Σ tier capacities`` regardless of uptime.  Snapshot fields
are flattened into scalar series per metric type:

* counter      → ``<name>.rate``  (delta/interval; restarts re-baseline
  instead of emitting a huge negative spike, counted in
  ``telemetry.counter_resets``)
* gauge        → ``<name>``
* histogram    → ``<name>.p50`` / ``<name>.p95`` / ``<name>.p99`` /
  ``<name>.rate``
* throughput   → ``<name>.rate`` (the meter's windowed rate)
* stage        → ``<name>.mean_s`` (incremental: Δtotal/Δcount, so a
  late regression is not diluted by healthy history) + ``<name>.rate``

Every :class:`~.exposition.TelemetryServer` serves the store at
``/timeline?metric=&since=&format=json|text``; the process-global
:data:`history` (over the global registry) starts lazily with the
first exporter unless ``DMLC_TIMELINE=0``.  The tracker and the
data-service dispatcher run a second store over their *merged* fleet
state, so one query answers "what did fleet ingest MB/s do over the
last hour".

Knobs: ``DMLC_TIMELINE`` (default 1), ``DMLC_TIMELINE_INTERVAL``
(sample cadence seconds, default 1.0), ``DMLC_TIMELINE_TIERS``
(``step_sxcount`` list, default ``1x300,10x360``),
``DMLC_TIMELINE_MAX_SERIES`` (default 512 — overflow series are
dropped and counted in ``telemetry.timeline.dropped_series``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import DMLCError, log_warning
from ..utils.metrics import metrics
from ..utils.parameter import get_env

__all__ = ["HistoryStore", "parse_tiers", "render_timeline_text",
           "history", "maybe_start_sampler", "TIMELINE_SCHEMA"]

TIMELINE_SCHEMA = "dmlc.telemetry.timeline/1"

#: (step_seconds, capacity) — tier 0 must be the finest
_DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = ((1.0, 300), (10.0, 360))


class TierSpecError(DMLCError):
    """Malformed ``DMLC_TIMELINE_TIERS`` — raised loudly at parse time."""


def parse_tiers(spec: str) -> List[Tuple[float, int]]:
    """``"1x300,10x360"`` → ``[(1.0, 300), (10.0, 360)]`` (step seconds
    × ring capacity per tier, finest first)."""
    tiers: List[Tuple[float, int]] = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        step, sep, count = clause.partition("x")
        if not sep:
            raise TierSpecError(f"tier {clause!r} is not STEPxCOUNT")
        try:
            tiers.append((float(step), int(count)))
        except ValueError:
            raise TierSpecError(f"bad tier {clause!r}") from None
    if not tiers:
        raise TierSpecError(f"empty tier spec {spec!r}")
    if any(s <= 0 or c <= 0 for s, c in tiers):
        raise TierSpecError(f"tiers must be positive: {spec!r}")
    if sorted(tiers) != tiers:
        raise TierSpecError(f"tiers must be finest-first: {spec!r}")
    return tiers


class _Series:
    """One scalar series: a ring per tier + the open downsample buckets."""

    __slots__ = ("rings", "buckets")

    def __init__(self, tiers: List[Tuple[float, int]]) -> None:
        self.rings: List[deque] = [deque(maxlen=cap) for _, cap in tiers]
        # per tier > 0: [bucket_id, sum, count] of the open bucket
        self.buckets: List[List[float]] = [[-1, 0.0, 0.0]
                                           for _ in tiers[1:]]

    def append(self, ts: float, value: float,
               tiers: List[Tuple[float, int]]) -> None:
        self.rings[0].append((ts, value))
        for i, (step, _cap) in enumerate(tiers[1:]):
            b = self.buckets[i]
            bucket = int(ts // step)
            if bucket != b[0]:
                if b[2] > 0:   # close the previous bucket as its mean
                    self.rings[i + 1].append((b[0] * step, b[1] / b[2]))
                b[0], b[1], b[2] = bucket, 0.0, 0.0
            b[1] += value
            b[2] += 1


class HistoryStore:
    """Bounded tiered time-series store over a snapshot source.

    ``snapshot_fn`` returns a snapshot-form dict (``{name: {"type": ...,
    ...}}``) — the global registry's :meth:`snapshot` by default, or the
    tracker/dispatcher's merged fleet view.  :meth:`sample_once` is the
    whole write path (the daemon thread just calls it on a cadence), so
    tests drive the store deterministically with an injected ``now``.
    """

    def __init__(self,
                 snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 tiers: Optional[List[Tuple[float, int]]] = None,
                 max_series: Optional[int] = None) -> None:
        if snapshot_fn is None:
            snapshot_fn = metrics.snapshot
        if tiers is None:
            tiers = parse_tiers(str(get_env("DMLC_TIMELINE_TIERS",
                                            "1x300,10x360")))
        if max_series is None:
            max_series = int(get_env("DMLC_TIMELINE_MAX_SERIES", 512))
        self.snapshot_fn = snapshot_fn
        self.tiers = list(tiers)
        self.max_series = int(max_series)
        self._series: Dict[str, _Series] = {}
        self._lock = threading.Lock()
        # counter/stage baselines for rate conversion (previous sample)
        self._prev: Dict[str, Tuple[float, float]] = {}   # name → (ts, val)
        self._prev_stage: Dict[str, Tuple[float, float]] = {}
        self._dropped: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- write path ------------------------------------------------------
    def _rate(self, key: str, now: float, value: float) -> Optional[float]:
        """Counter → per-second rate against the previous sample; a
        counter that went BACKWARDS (process restart behind a merged
        view) re-baselines at the new value instead of emitting a huge
        negative spike."""
        prev = self._prev.get(key)
        self._prev[key] = (now, value)
        if prev is None:
            return None
        pts, pval = prev
        dt = now - pts
        if dt <= 0:
            return None
        if value < pval:
            metrics.counter("telemetry.counter_resets").add(1)
            pval = 0.0
        return (value - pval) / dt

    def _stage_mean(self, name: str, count: float, total: float
                    ) -> Optional[float]:
        prev = self._prev_stage.get(name)
        self._prev_stage[name] = (count, total)
        if prev is None:
            return None
        pc, pt = prev
        if count < pc:          # restarted worker: incremental from zero
            pc, pt = 0.0, 0.0
        if count <= pc:
            return None         # no new calls this interval — no point
        return (total - pt) / (count - pc)

    def _extract(self, now: float, snapshot: Dict[str, Any]
                 ) -> Dict[str, float]:
        points: Dict[str, float] = {}
        for name, snap in snapshot.items():
            if not isinstance(snap, dict):
                continue
            t = snap.get("type")
            if t == "counter":
                r = self._rate(name, now, float(snap.get("value", 0)))
                if r is not None:
                    points[f"{name}.rate"] = r
            elif t == "gauge":
                v = snap.get("value")
                if isinstance(v, (int, float)):
                    points[name] = float(v)
            elif t == "histogram":
                # p95 joined p50/p99 for the tail sampler's adaptive
                # keep-slow threshold (live p95 of the root span name)
                for f in ("p50", "p95", "p99"):
                    v = snap.get(f)
                    if isinstance(v, (int, float)):
                        points[f"{name}.{f}"] = float(v)
                r = self._rate(f"{name}.count", now,
                               float(snap.get("count", 0)))
                if r is not None:
                    points[f"{name}.rate"] = r
            elif t == "throughput":
                v = snap.get("windowed_rate")
                if isinstance(v, (int, float)):
                    points[f"{name}.rate"] = float(v)
            elif t == "stage":
                m = self._stage_mean(name, float(snap.get("count", 0)),
                                     float(snap.get("total_sec", 0.0)))
                if m is not None:
                    points[f"{name}.mean_s"] = m
                r = self._rate(f"{name}.calls", now,
                               float(snap.get("count", 0)))
                if r is not None:
                    points[f"{name}.rate"] = r
        return points

    def sample_once(self, now: Optional[float] = None) -> int:
        """One sampling pass; returns the number of points recorded.
        The thread calls this on the cadence; tests call it directly
        with a synthetic clock."""
        if now is None:
            now = time.time()
        try:
            snapshot = self.snapshot_fn()
        except Exception as e:   # sampling must never kill the process
            log_warning("timeline sampler: snapshot failed: %s", e)
            return 0
        points = self._extract(now, snapshot)
        metrics.counter("telemetry.timeline.samples").add(1)
        with self._lock:
            for name, value in points.items():
                series = self._series.get(name)
                if series is None:
                    if len(self._series) >= self.max_series:
                        if name not in self._dropped:
                            self._dropped.add(name)
                            metrics.counter(
                                "telemetry.timeline.dropped_series").add(1)
                        continue
                    series = self._series[name] = _Series(self.tiers)
                series.append(now, value, self.tiers)
        return len(points)

    # -- read path -------------------------------------------------------
    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, name: str, since: Optional[float] = None,
              now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Points of one series covering the last ``since`` seconds, from
        the finest tier whose span covers the window (burn-rate rules
        read through this, so an hour-long window transparently lands on
        the downsampled tier)."""
        if now is None:
            now = time.time()
        cutoff = None if since is None else now - float(since)
        with self._lock:
            series = self._series.get(name)
            if series is None:
                return []
            tier = len(self.tiers) - 1
            if since is not None:
                for i, (step, cap) in enumerate(self.tiers):
                    if step * cap >= float(since):
                        tier = i
                        break
            pts = list(series.rings[tier])
        if cutoff is None:
            return pts
        return [(ts, v) for ts, v in pts if ts >= cutoff]

    def timeline(self, metric: Optional[str] = None,
                 since: Optional[float] = None) -> Dict[str, Any]:
        """The ``/timeline`` document.  Without ``metric``: the index
        (series names + tier config).  With ``metric``: every series of
        that metric (exact name or ``metric.<field>``), all tiers,
        filtered to the last ``since`` seconds."""
        now = time.time()
        doc: Dict[str, Any] = {
            "schema": TIMELINE_SCHEMA, "now": now,
            "tiers": [{"step_s": s, "capacity": c} for s, c in self.tiers],
        }
        with self._lock:
            names = sorted(self._series)
            if metric is None:
                doc["series"] = names
                doc["series_count"] = len(names)
                return doc
            matched = [n for n in names
                       if n == metric or n.startswith(metric + ".")]
            cutoff = None if since is None else now - float(since)
            out: Dict[str, Any] = {}
            for n in matched:
                tiers_out = []
                for i, (step, _cap) in enumerate(self.tiers):
                    pts = list(self._series[n].rings[i])
                    if cutoff is not None:
                        pts = [p for p in pts if p[0] >= cutoff]
                    tiers_out.append({"step_s": step,
                                      "points": [[ts, v] for ts, v in pts]})
                out[n] = {"tiers": tiers_out}
        doc["metric"] = metric
        doc["series"] = out
        return doc

    def snapshot_doc(self, since: Optional[float] = None) -> Dict[str, Any]:
        """Every series, every tier — what a flight bundle attaches as
        ``timeline.json`` (bounded by the ring capacities, so the slice
        is the whole store)."""
        now = time.time()
        cutoff = None if since is None else now - float(since)
        with self._lock:
            series: Dict[str, Any] = {}
            for n in sorted(self._series):
                tiers_out = []
                for i, (step, _cap) in enumerate(self.tiers):
                    pts = list(self._series[n].rings[i])
                    if cutoff is not None:
                        pts = [p for p in pts if p[0] >= cutoff]
                    tiers_out.append({"step_s": step,
                                      "points": [[ts, v] for ts, v in pts]})
                series[n] = {"tiers": tiers_out}
        return {"schema": TIMELINE_SCHEMA, "now": now,
                "tiers": [{"step_s": s, "capacity": c}
                          for s, c in self.tiers],
                "series": series}

    # -- lifecycle -------------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> "HistoryStore":
        """Start the daemon sampler (idempotent)."""
        if self._thread is not None:
            return self
        if interval_s is None:
            interval_s = float(get_env("DMLC_TIMELINE_INTERVAL", 1.0))
        interval_s = max(0.01, float(interval_s))

        def _run() -> None:
            while not self._stop.wait(interval_s):
                self.sample_once()

        self._stop.clear()
        self._thread = threading.Thread(target=_run, name="dmlc-timeline",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    @property
    def running(self) -> bool:
        return self._thread is not None


_SPARK = " .:-=+*#%@"


def _sparkline(values: List[float]) -> str:
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK[1] * len(values)
    return "".join(_SPARK[1 + int((v - lo) / span * (len(_SPARK) - 2))]
                   for v in values)


def render_timeline_text(doc: Dict[str, Any]) -> str:
    """``/timeline?format=text``: one line per series — last value,
    min/max over the window, and an ASCII sparkline of the finest tier
    (legible through ``curl``, no tooling required)."""
    series = doc.get("series")
    if isinstance(series, list):         # index document
        lines = ["timeline series:"]
        lines.extend(f"  {n}" for n in series)
        return "\n".join(lines) + "\n"
    lines = []
    for name in sorted(series or {}):
        tiers = (series[name] or {}).get("tiers", [])
        pts = tiers[0].get("points", []) if tiers else []
        vals = [v for _ts, v in pts]
        if not vals:
            lines.append(f"{name}: (no samples in window)")
            continue
        lines.append(f"{name}: last={vals[-1]:g} min={min(vals):g} "
                     f"max={max(vals):g} n={len(vals)} "
                     f"[{_sparkline(vals[-60:])}]")
        for t in tiers[1:]:
            tv = [v for _ts, v in t.get("points", [])]
            if tv:
                lines.append(f"  @{t.get('step_s', 0):g}s: n={len(tv)} "
                             f"[{_sparkline(tv[-60:])}]")
    if not lines:
        lines = ["timeline: no matching series"]
    return "\n".join(lines) + "\n"


#: process-global store over the global registry — what every default
#: ``TelemetryServer`` serves at ``/timeline`` and what flight bundles
#: attach as ``timeline.json``
history = HistoryStore()


def maybe_start_sampler() -> Optional[HistoryStore]:
    """Start the global sampler unless ``DMLC_TIMELINE=0``.  Idempotent —
    every exporter start funnels through here, matching the
    ``maybe_*_from_env`` convention of flight/anomaly."""
    if not get_env("DMLC_TIMELINE", True):
        return None
    return history.start()
