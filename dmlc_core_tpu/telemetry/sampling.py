"""Tail-based trace sampling: decide keep/drop per *trace*, after the fact.

The recorder ring (``trace.py``) keeps every span, so at production
rates the traces worth keeping — the p99 stragglers, the hedged
resubmits, the SLO-breach windows — are evicted by a flood of healthy
requests within milliseconds.  Head sampling (flip a coin at the root)
cannot fix that: the whole point of a trace is that you do not know it
will be interesting until it is over.  This module implements the
Dapper→Canopy answer adapted to this tree's span vocabulary:

* finished spans buffer per ``trace_id`` in a bounded
  :class:`TraceBuffer` until every locally-open span of the trace has
  ended (or ``DMLC_TRACE_DECIDE_TIMEOUT_S`` passes);
* a :class:`TailSampler` then keeps the trace iff any span errored, the
  local root ran longer than ``DMLC_TRACE_KEEP_SLOW_MS`` (default:
  adaptive — the live p95 of that root span name, fed through the
  registry and readable back from the r14 ``HistoryStore``), an
  SLO/burn breach was active, or the trace falls inside the consistent
  hash floor ``DMLC_TRACE_SAMPLE``;
* the hash floor is a pure function of the ``trace_id`` already carried
  in the serving request header and the data-service JSON RPCs, so the
  router, replica, worker and dispatcher reach the **same** verdict for
  the same trace without exchanging a single byte of coordination;
* a token bucket (``DMLC_TRACE_KEEP_PER_S``) bounds the keep rate;
  error/debug keeps always pass but still debit the bucket, so the
  total stays near budget while nothing alarming is lost;
* bit 63 of the wire ``trace_id`` is the ``debug=1`` flag
  (:func:`mark_debug`): it rides the existing serving header and
  data-service JSON keys unchanged and forces keep on every tier.

Kept traces flow into the existing :data:`~.trace.recorder` (and from
there to ``/spans``, the Chrome export and flight bundles) unchanged.
Drops are counted (``telemetry.sampling.{dropped,dropped_spans}``),
never silent.

The sampler is *opt-in*: :func:`maybe_install_from_env` installs it only
when ``DMLC_TRACE_SAMPLE`` is set, so untraced deployments and the
existing tests keep the record-everything behaviour.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..utils.metrics import metrics
from ..utils.parameter import get_env
from . import trace as _trace
from .timeseries import history

__all__ = [
    "DEBUG_BIT", "TailSampler", "TraceBuffer", "hash_keep", "is_debug",
    "mark_debug", "debug_trace_id", "get_sampler", "install", "uninstall",
    "maybe_install_from_env", "was_kept",
]

_M64 = (1 << 64) - 1
#: bit 63 of the wire trace id: the end-to-end force-keep ("debug") flag.
#: :func:`~.trace.new_trace_id` only mints 63-bit ids, so the bit is
#: never set by accident — only by :func:`mark_debug` at the edge.
DEBUG_BIT = 1 << 63
_ID_MASK = DEBUG_BIT - 1

#: statuses that do NOT make a trace an error trace
_OK_STATUSES = {"OK", "ok", None}


def is_debug(trace_id: int) -> bool:
    """True when the wire id carries the force-keep bit."""
    return bool(int(trace_id) & DEBUG_BIT)


def mark_debug(ctx: "_trace.TraceContext") -> "_trace.TraceContext":
    """Stamp the debug bit onto a context; every tier the ids reach
    (serving header, data-service JSON keys) then force-keeps the
    trace regardless of sampling verdicts."""
    return _trace.TraceContext(ctx.trace_id | DEBUG_BIT, ctx.span_id)


def debug_trace_id() -> int:
    """A fresh trace id with the force-keep bit already set."""
    return _trace.new_trace_id() | DEBUG_BIT


def _mix(trace_id: int) -> int:
    """splitmix64-style finalizer: a stable, well-distributed hash of
    the id that every process computes identically (``hash()`` is
    randomized per process and would break cross-tier agreement)."""
    x = (int(trace_id) & _ID_MASK) or 1
    x = ((x ^ (x >> 33)) * 0xFF51AFD7ED558CCD) & _M64
    x = ((x ^ (x >> 33)) * 0xC4CEB9FE1A85EC53) & _M64
    return (x ^ (x >> 33)) & _M64


def hash_keep(trace_id: int, floor: float) -> bool:
    """Consistent hash floor: the same ``trace_id`` lands on the same
    side of ``floor`` in every process, so tiers agree coordination-free."""
    if floor >= 1.0:
        return True
    if floor <= 0.0:
        return False
    return _mix(trace_id) < int(floor * float(1 << 64))


class _TokenBucket:
    """Keep-rate bound.  ``rate <= 0`` means unlimited.  ``take(force=
    True)`` (error/debug keeps) always succeeds but still debits, so
    forced keeps push the bucket into debt and healthy keeps pay it
    back — total keep rate stays near budget."""

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self._tokens = self.burst
        self._t = time.monotonic()

    def take(self, *, force: bool = False, now: Optional[float] = None
             ) -> bool:
        if self.rate <= 0:
            return True
        if now is None:
            now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= 1.0 or force:
            self._tokens -= 1.0
            return True
        return False


class _Group:
    """All buffered records of one trace on this process, plus the count
    of spans started but not yet ended locally."""

    __slots__ = ("trace_id", "t0", "open", "records")

    def __init__(self, trace_id: int, t0: float) -> None:
        self.trace_id = trace_id
        self.t0 = t0
        self.open = 0
        self.records: List[Dict[str, Any]] = []


class TraceBuffer:
    """Bounded per-trace staging area for finished span records.

    ``on_start``/``on_end`` mirror the local span lifecycle; when the
    open count of a trace returns to zero (the local root ended) the
    owner's ``decide`` callback fires with the full group.  Groups older
    than ``decide_timeout_s`` are decided on whatever is buffered, and
    when the total buffered span count would exceed ``max_spans`` the
    oldest group is force-decided — the buffer can stall a verdict, but
    it can never grow without bound or swallow spans silently.
    """

    def __init__(self, decide, *, max_spans: int = 8192,
                 decide_timeout_s: float = 5.0) -> None:
        self._decide = decide
        self.max_spans = max(1, int(max_spans))
        self.decide_timeout_s = max(0.05, float(decide_timeout_s))
        self._lock = threading.Lock()
        self._groups: "OrderedDict[int, _Group]" = OrderedDict()
        self._spans = 0
        self._last_sweep = 0.0

    def __len__(self) -> int:
        with self._lock:
            return self._spans

    def on_start(self, trace_id: int, now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        with self._lock:
            g = self._groups.get(trace_id)
            if g is None:
                g = self._groups[trace_id] = _Group(trace_id, now)
            g.open += 1
        self._sweep(now)

    def on_end(self, trace_id: int, rec: Dict[str, Any],
               now: Optional[float] = None) -> None:
        if now is None:
            now = time.monotonic()
        done: List[_Group] = []
        with self._lock:
            g = self._groups.get(trace_id)
            if g is None:
                # sampler installed mid-span, or a span whose start
                # predates the buffer: a group of its own, decided now
                g = _Group(trace_id, now)
                g.records.append(rec)
                done.append(g)
            else:
                g.records.append(rec)
                self._spans += 1
                g.open -= 1
                if g.open <= 0:
                    self._groups.pop(trace_id, None)
                    self._spans -= len(g.records)
                    done.append(g)
            while self._spans > self.max_spans and self._groups:
                _tid, old = self._groups.popitem(last=False)
                self._spans -= len(old.records)
                metrics.counter("telemetry.sampling.overflow").add(1)
                done.append(old)
        for g in done:
            self._decide(g, timed_out=False)
        self._sweep(now)

    def attach(self, trace_id: int, rec: Dict[str, Any]) -> bool:
        """Buffer a standalone event record with its trace's group.
        False when no group is open (caller applies the cached verdict
        or records directly)."""
        with self._lock:
            g = self._groups.get(trace_id)
            if g is None:
                return False
            g.records.append(rec)
            self._spans += 1
        return True

    def flush_expired(self, now: Optional[float] = None) -> int:
        """Decide every group older than the timeout on whatever is
        buffered (remote-rooted traces whose parent never ends locally,
        leaked spans).  Returns the number of groups decided."""
        if now is None:
            now = time.monotonic()
        expired: List[_Group] = []
        with self._lock:
            cutoff = now - self.decide_timeout_s
            for tid in list(self._groups):
                g = self._groups[tid]
                if g.t0 > cutoff:
                    break           # insertion-ordered: the rest is newer
                del self._groups[tid]
                self._spans -= len(g.records)
                expired.append(g)
        for g in expired:
            metrics.counter("telemetry.sampling.timeouts").add(1)
            self._decide(g, timed_out=True)
        return len(expired)

    def _sweep(self, now: float) -> None:
        # cheap lazy expiry: at most one pass per second, driven by the
        # span lifecycle itself (no background thread to leak).  The
        # first check is deliberately lock-free — it runs on every span
        # end, and a stale read just defers the sweep to the next span
        if now - self._last_sweep < 1.0:
            return
        with self._lock:
            if now - self._last_sweep < 1.0:
                return
            self._last_sweep = now
        self.flush_expired(now)


class TailSampler:
    """Keep/drop verdicts over completed trace groups.

    Installed via :func:`install` it intercepts the recorder feed in
    ``trace.py``; kept groups flush into the untouched global
    :data:`~.trace.recorder`, dropped ones are counted and discarded.
    Verdicts are cached (bounded) so late spans and exemplar lookups
    (:func:`was_kept`) agree with the decision.
    """

    def __init__(self, *, floor: Optional[float] = None,
                 keep_per_s: Optional[float] = None,
                 keep_slow_ms: Optional[float] = None,
                 decide_timeout_s: Optional[float] = None,
                 max_spans: Optional[int] = None,
                 recorder: Optional["_trace.SpanRecorder"] = None) -> None:
        if floor is None:
            floor = float(get_env("DMLC_TRACE_SAMPLE", 0.01))
        if keep_per_s is None:
            keep_per_s = float(get_env("DMLC_TRACE_KEEP_PER_S", 0.0))
        if keep_slow_ms is None:
            raw = get_env("DMLC_TRACE_KEEP_SLOW_MS", None)
            keep_slow_ms = float(raw) if raw is not None else 0.0
        if decide_timeout_s is None:
            decide_timeout_s = float(get_env("DMLC_TRACE_DECIDE_TIMEOUT_S",
                                             5.0))
        if max_spans is None:
            max_spans = int(get_env("DMLC_TRACE_BUFFER_SPANS", 8192))
        self.floor = max(0.0, min(1.0, float(floor)))
        #: 0 = adaptive (live p95 of the root span name)
        self.keep_slow_ms = max(0.0, float(keep_slow_ms))
        self.recorder = recorder if recorder is not None else _trace.recorder
        self._bucket = _TokenBucket(keep_per_s)
        self.buffer = TraceBuffer(self._decide, max_spans=max_spans,
                                  decide_timeout_s=decide_timeout_s)
        self._lock = threading.Lock()
        self._verdicts: "OrderedDict[int, bool]" = OrderedDict()
        self._verdict_cap = 4096
        #: root name → (expires_at, threshold) — the adaptive slow
        #: threshold reads a histogram snapshot (a quantile sort); once
        #: per second per root is signal enough, per-decide is not
        self._thr_cache: Dict[str, Tuple[float, Optional[float]]] = {}
        self._bind()

    # -- trace.py hook surface ------------------------------------------
    def on_start(self, trace_id: int) -> None:
        # sticky verdicts: a span of an already-decided trace must not
        # reopen a group (each late tier-span would otherwise trigger a
        # fresh decision — and a fresh adaptive-p95 computation — per
        # span, tripling the sampler's cost on multi-span traces)
        if self.verdict(trace_id) is None:
            self.buffer.on_start(trace_id)

    def on_end(self, trace_id: int, rec: Dict[str, Any]) -> None:
        v = self.verdict(trace_id)
        if v is None:
            self.buffer.on_end(trace_id, rec)
        elif v:
            self.recorder.record(rec)
        else:
            if self._mgen != metrics.generation:
                self._bind()
            self._m_dropped_spans.add(1)

    def on_event(self, trace_id: Optional[int], rec: Dict[str, Any]) -> None:
        """Standalone instant events: buffered with their trace when one
        is open, else routed by the cached verdict, else recorded
        directly (untraced events — breaker trips etc. — always land)."""
        if trace_id is None:
            self.recorder.record(rec)
            return
        if self.buffer.attach(trace_id, rec):
            return
        if self.verdict(trace_id) is False:
            if self._mgen != metrics.generation:
                self._bind()
            self._m_dropped_spans.add(1)
            return
        self.recorder.record(rec)

    # -- verdicts --------------------------------------------------------
    def verdict(self, trace_id: int) -> Optional[bool]:
        """Cached keep/drop for a decided trace; None while undecided.

        Lock-free read on the span hot path: ``dict.get`` is atomic
        under the GIL and ``_cache`` is the only writer (under
        ``_lock``) — the worst race returns ``None`` for a verdict
        cached this instant, which just routes one span through the
        buffer's decided-group path."""
        return self._verdicts.get(int(trace_id) & _ID_MASK)

    def was_kept(self, trace_hex: Optional[str]) -> Optional[bool]:
        """Verdict lookup by the hex id records/exemplars carry."""
        if not trace_hex:
            return None
        try:
            return self.verdict(int(trace_hex, 16))
        except ValueError:
            return None

    def flush(self) -> None:
        """Decide every buffered group now (tests, shutdown paths)."""
        self.buffer.flush_expired(now=time.monotonic()
                                  + self.buffer.decide_timeout_s + 1.0)

    def _cache(self, trace_id: int, keep: bool) -> None:
        with self._lock:
            self._verdicts[int(trace_id) & _ID_MASK] = keep
            while len(self._verdicts) > self._verdict_cap:
                self._verdicts.popitem(last=False)

    # -- the decision ----------------------------------------------------
    @staticmethod
    def _is_error(rec: Dict[str, Any]) -> bool:
        attrs = rec.get("attrs") or {}
        if attrs.get("error") is not None:
            return True
        return attrs.get("status") not in _OK_STATUSES

    @staticmethod
    def _root_of(records: List[Dict[str, Any]]
                 ) -> Optional[Dict[str, Any]]:
        """The local root: a span whose parent ended elsewhere (or
        nowhere).  Longest such span wins when several qualify."""
        spans = [r for r in records if r.get("kind") == "span"]
        if not spans:
            return None
        local = {r.get("span_id") for r in spans}
        roots = [r for r in spans
                 if not r.get("parent_id") or r["parent_id"] not in local]
        return max(roots or spans, key=lambda r: r.get("dur_us", 0))

    def _slow_threshold_ms(self, root_name: str) -> Optional[float]:
        """Explicit knob, or adaptive: the live p95 of this root span
        name — preferring the HistoryStore series (it survives registry
        resets and powers ``/timeline``), falling back to the live
        histogram the sampler itself feeds."""
        if self.keep_slow_ms > 0:
            return self.keep_slow_ms
        now = time.monotonic()
        hit = self._thr_cache.get(root_name)
        if hit is not None and hit[0] > now:
            return hit[1]
        series = f"telemetry.trace.root_ms.{root_name}"
        pts = history.query(series + ".p95", since=300.0)
        if pts:
            thr: Optional[float] = pts[-1][1]
        else:
            snap = metrics.histogram(series).snapshot()
            thr = (float(snap["p95"]) if snap.get("count", 0) >= 50
                   else None)      # not enough signal yet — no slow keeps
        if len(self._thr_cache) >= 256:      # root names are bounded by
            self._thr_cache.clear()          # the span vocabulary anyway
        self._thr_cache[root_name] = (now + 1.0, thr)
        return thr

    def _bind(self) -> None:
        """(Re)resolve metric handles for the current registry
        generation — the decide path runs per trace, and a registry
        lookup (lock + dict) per counter per trace is measurable at
        production rates.  ``metrics.reset()`` bumps ``generation``, so
        cached handles never go stale across test resets."""
        self._mgen = metrics.generation
        self._m_slo = metrics.gauge("slo.active_breaches")
        self._m_throttled = metrics.counter("telemetry.sampling.throttled")
        self._m_kept = metrics.counter("telemetry.sampling.kept")
        self._m_dropped = metrics.counter("telemetry.sampling.dropped")
        self._m_dropped_spans = metrics.counter(
            "telemetry.sampling.dropped_spans")
        self._m_keep: Dict[str, Any] = {}
        self._m_root: Dict[str, Any] = {}

    def _decide(self, group: _Group, *, timed_out: bool) -> None:
        records = group.records
        if not records:
            return
        if self._mgen != metrics.generation:
            self._bind()
        tid = group.trace_id
        reason = None
        if is_debug(tid):
            reason = "debug"
        elif any(self._is_error(r) for r in records):
            reason = "error"
        elif self._m_slo.value > 0:
            reason = "slo"
        root = self._root_of(records)
        if root is not None and not timed_out:
            # feed the adaptive threshold with *every* root latency —
            # the p95 must reflect all traffic, not just kept traces
            dur_ms = root.get("dur_us", 0) / 1e3
            name = root["name"]
            h = self._m_root.get(name)
            if h is None:
                # 512 reservoir samples give a stable-enough p95 and keep
                # the once-per-second threshold snapshot's sort cheap
                h = self._m_root[name] = metrics.histogram(
                    f"telemetry.trace.root_ms.{name}", max_samples=512)
            h.observe(dur_ms)
            if reason is None:
                thr = self._slow_threshold_ms(name)
                if thr is not None and dur_ms > thr:
                    reason = "slow"
        if reason is None and hash_keep(tid, self.floor):
            reason = "floor"
        if reason is None:
            keep = False
        elif reason in ("debug", "error"):
            keep = True
            self._bucket.take(force=True)
        else:
            keep = self._bucket.take()
            if not keep:
                self._m_throttled.add(1)
        self._cache(tid, keep)
        if keep:
            self._m_kept.add(1)
            c = self._m_keep.get(reason)
            if c is None:
                c = self._m_keep[reason] = metrics.counter(
                    f"telemetry.sampling.keep_{reason}")
            c.add(1)
            for rec in records:
                self.recorder.record(rec)
        else:
            self._m_dropped.add(1)
            self._m_dropped_spans.add(len(records))


# -- installation ---------------------------------------------------------

def get_sampler() -> Optional[TailSampler]:
    """The installed sampler (what trace.py feeds), or None."""
    return _trace.get_sampler()


def install(sampler: TailSampler) -> TailSampler:
    """Route the span feed through ``sampler`` (replacing any prior)."""
    _trace.set_sampler(sampler)
    return sampler


def uninstall() -> None:
    """Restore record-everything behaviour."""
    _trace.set_sampler(None)


def was_kept(trace_hex: Optional[str]) -> Optional[bool]:
    """Module-level verdict lookup: True/False once decided, None when
    undecided or when no sampler is installed (everything is kept)."""
    s = get_sampler()
    if s is None:
        return None
    return s.was_kept(trace_hex)


def maybe_install_from_env() -> Optional[TailSampler]:
    """Install a :class:`TailSampler` iff ``DMLC_TRACE_SAMPLE`` is set
    (the opt-in switch), idempotently — every tier's startup path calls
    this, matching the ``maybe_*_from_env`` convention of
    flight/anomaly/timeseries."""
    if get_env("DMLC_TRACE_SAMPLE", None) is None:
        return None
    existing = get_sampler()
    if existing is not None:
        return existing
    return install(TailSampler())
