"""Streaming anomaly & straggler detection + declarative SLO rules.

PR 3 made the numbers visible; this module makes them *judge themselves*.
Three cooperating pieces, all stdlib, all cheap enough to leave on:

* :class:`StreamingStat` / :class:`StallDetector` — per-process EWMA +
  MAD z-scores over a stage's recent durations.  A pipeline stage that
  suddenly takes 10x its typical time (wedged reader, GC storm, noisy
  neighbor) flags ``anomaly.stall_z.<stage>`` / ``anomaly.stalls.<stage>``
  and drops a note into the flight recorder — the tf.data papers' input
  bottleneck attribution (arxiv 2101.12127, 2210.14826), done streaming.

* :class:`StragglerBoard` — the tracker-side twin: cross-RANK comparison
  over the rank-tagged registry states workers already push
  (``cmd=telemetry``).  For every stage metric it derives each rank's
  *incremental* mean (delta total / delta count between pushes, so a
  late-onset straggler is not diluted by its healthy history), smooths it
  with an EWMA, and flags ranks whose smoothed time sits a robust
  z-score above the fleet median.  Flags surface as per-rank
  ``straggler_suspect`` / ``straggler_z`` gauges on the tracker
  ``/metrics`` and as JSON on ``/stragglers``.

* :class:`SloMonitor` + the ``DMLC_SLO_SPEC`` grammar — declarative
  service-level objectives over any registry snapshot, mirroring the
  ``DMLC_FAULT_SPEC`` site grammar (same clause shape, same loud parse
  errors, same exact-no-op-when-unset contract)::

      spec  := rule (',' rule)*
      rule  := metric (':' key '=' value)*

      keys:
        max=V     breach when the observed field exceeds V
        min=V     breach when the observed field falls below V
                  (V takes ms/s duration suffixes: "50ms", "1.5s")
        field=F   snapshot field to test; defaults by metric type:
                  gauge/counter → value, histogram → p99,
                  throughput → windowed_rate, stage → mean_sec
        for=N     consecutive breached evaluations before firing
                  (default 1 — a single bad sample is a page)

  Example::

      DMLC_SLO_SPEC='serving.latency_s:field=p99:max=50ms,serving.batcher.queue_depth:max=192'

  A firing rule bumps ``slo.breaches``, holds ``slo.active_breaches``
  at the number of currently-breached rules (the serving health gauge
  reads this and degrades), and triggers a flight-recorder dump naming
  the rule — closing the loop from "metric exists" to "the system tells
  you what is wrong and hands you the evidence".
"""

from __future__ import annotations

import sys
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import DMLCError, log_warning
from ..utils.metrics import MetricsRegistry, metrics
from ..utils.parameter import get_env

__all__ = [
    "StreamingStat", "StallDetector", "StragglerBoard",
    "SloRule", "SloSpecError", "SloMonitor", "parse_slo_spec",
    "maybe_monitor_from_env", "active_slo_spec",
]

SLO_ENV_VAR = "DMLC_SLO_SPEC"


def _flight_mod():
    """The flight recorder, if loaded — via sys.modules so this module
    never hard-imports it (flight imports nothing from here either; the
    two meet only at runtime)."""
    return sys.modules.get("dmlc_core_tpu.telemetry.flight")


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class StreamingStat:
    """EWMA mean + EWMA absolute-deviation scale, with robust z-scores.

    MAD-style: the deviation estimate tracks ``|x - mean|`` rather than
    squared error, so one huge outlier cannot inflate the scale enough
    to hide the next one.  ``1.4826`` converts a MAD to a Gaussian
    sigma-equivalent so thresholds read in familiar units.
    """

    __slots__ = ("alpha", "mean", "dev", "n")

    def __init__(self, alpha: float = 0.25) -> None:
        self.alpha = float(alpha)
        self.mean: Optional[float] = None
        self.dev = 0.0
        self.n = 0

    def zscore(self, x: float, rel_floor: float = 0.0) -> float:
        """Robust z of ``x`` against the CURRENT estimate (call before
        :meth:`update` so a sample is judged by its history, not itself).
        ``rel_floor`` sets a minimum scale as a fraction of the mean so
        tiny absolute jitter on a quiet stream can't produce huge z."""
        if self.mean is None or self.n < 1:
            return 0.0
        scale = max(1.4826 * self.dev, rel_floor * abs(self.mean), 1e-12)
        return (x - self.mean) / scale

    def update(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.mean is None:
            self.mean = x
            return
        self.dev += self.alpha * (abs(x - self.mean) - self.dev)
        self.mean += self.alpha * (x - self.mean)


class StallDetector:
    """Per-stage stall flagging from a stream of durations.

    ``observe(dur_s)`` is the whole API: compute the robust z against the
    stage's own history, update the estimate, and when the z clears the
    threshold after a warm-up count, flag it (gauge + counter + flight
    note).  ``DMLC_STALL_Z`` <= 0 disables flagging (observation still
    updates, so re-enabling doesn't start cold).
    """

    def __init__(self, name: str, z_threshold: Optional[float] = None,
                 min_samples: int = 16, alpha: float = 0.25,
                 rel_floor: float = 0.5) -> None:
        self.name = name
        if z_threshold is None:
            z_threshold = get_env("DMLC_STALL_Z", 8.0)
        self.z_threshold = float(z_threshold)
        self.min_samples = int(min_samples)
        self.rel_floor = float(rel_floor)
        self._stat = StreamingStat(alpha=alpha)
        self._lock = threading.Lock()
        self._m_gen = -1
        self._bind()

    def _bind(self) -> None:
        self._m_gen = metrics.generation
        self._m_z = metrics.gauge(f"anomaly.stall_z.{self.name}")
        self._m_stalls = metrics.counter(f"anomaly.stalls.{self.name}")

    def observe(self, dur_s: float) -> float:
        """Feed one duration; returns the z-score it was judged at."""
        with self._lock:
            z = self._stat.zscore(dur_s, rel_floor=self.rel_floor)
            self._stat.update(dur_s)
            n = self._stat.n
        if self._m_gen != metrics.generation:
            self._bind()
        self._m_z.set(z)
        if (self.z_threshold > 0 and n > self.min_samples
                and z > self.z_threshold):
            self._m_stalls.add(1)
            log_warning("anomaly: stage %r stalled (%.4fs, z=%.1f over "
                        "EWMA %.4fs)", self.name, dur_s, z,
                        self._stat.mean or 0.0)
            fl = _flight_mod()
            if fl is not None:
                fl.note("stage_stall", stage=self.name,
                        dur_s=float(dur_s), z=float(z))
        return z


class StragglerBoard:
    """Tracker-side cross-rank straggler detection over telemetry pushes.

    ``update(rank, state)`` ingests one rank-tagged registry state (the
    ``cmd=telemetry`` payload).  For each stage-type metric it computes
    the incremental mean since that rank's previous push and folds it
    into a per-(rank, stage) EWMA.  ``evaluate()`` compares ranks: for
    each stage reported by at least ``min_ranks`` ranks, a rank whose
    EWMA sits more than ``z_threshold`` robust z-scores above the fleet
    median (MAD across ranks, floored at ``rel_floor`` of the median) is
    a straggler suspect.
    """

    def __init__(self, z_threshold: Optional[float] = None,
                 min_ranks: int = 3, alpha: float = 0.4,
                 rel_floor: Optional[float] = None) -> None:
        if z_threshold is None:
            z_threshold = get_env("DMLC_STRAGGLER_Z", 4.0)
        if rel_floor is None:
            rel_floor = get_env("DMLC_STRAGGLER_REL_FLOOR", 0.1)
        self.z_threshold = float(z_threshold)
        self.min_ranks = int(min_ranks)
        self.rel_floor = float(rel_floor)
        self._alpha = float(alpha)
        self._lock = threading.Lock()
        # rank → stage → EWMA of incremental mean seconds
        self._ewma: Dict[str, Dict[str, StreamingStat]] = {}
        # rank → stage → (count, total_sec) at the previous push
        self._prev: Dict[str, Dict[str, Tuple[int, float]]] = {}

    def update(self, rank: Any, state: Dict[str, Dict[str, Any]]) -> None:
        rank = str(rank)
        with self._lock:
            prev = self._prev.setdefault(rank, {})
            ewma = self._ewma.setdefault(rank, {})
            for name, s in (state or {}).items():
                if not isinstance(s, dict) or s.get("type") != "stage":
                    continue
                count = int(s.get("count", 0))
                total = float(s.get("total_sec", 0.0))
                pc, pt = prev.get(name, (0, 0.0))
                if count < pc:          # rank restarted: counters reset
                    pc, pt = 0, 0.0
                prev[name] = (count, total)
                if count <= pc:
                    continue            # no new work since the last push
                inc_mean = (total - pt) / (count - pc)
                ewma.setdefault(name, StreamingStat(self._alpha)) \
                    .update(inc_mean)

    def evaluate(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """``{stage: {rank: {"mean_s", "z", "straggler"}}}`` for every
        stage with at least ``min_ranks`` reporting ranks."""
        with self._lock:
            by_stage: Dict[str, Dict[str, float]] = {}
            for rank, stages in self._ewma.items():
                for stage, stat in stages.items():
                    if stat.mean is not None:
                        by_stage.setdefault(stage, {})[rank] = stat.mean
        out: Dict[str, Dict[str, Dict[str, Any]]] = {}
        for stage, per_rank in by_stage.items():
            if len(per_rank) < self.min_ranks:
                continue
            means = list(per_rank.values())
            med = _median(means)
            mad = _median([abs(m - med) for m in means])
            scale = max(1.4826 * mad, self.rel_floor * abs(med), 1e-12)
            out[stage] = {
                rank: {"mean_s": m, "z": (m - med) / scale,
                       "straggler": (m - med) / scale > self.z_threshold}
                for rank, m in per_rank.items()}
        return out

    def suspects(self) -> List[str]:
        """Ranks flagged on at least one stage, sorted."""
        flagged = {rank
                   for per_rank in self.evaluate().values()
                   for rank, d in per_rank.items() if d["straggler"]}
        return sorted(flagged, key=str)

    def snapshot(self) -> Dict[str, Any]:
        """JSON body of the tracker's ``/stragglers`` endpoint."""
        stages = self.evaluate()
        return {
            "z_threshold": self.z_threshold,
            "min_ranks": self.min_ranks,
            "stages": stages,
            "stragglers": sorted(
                {r for pr in stages.values()
                 for r, d in pr.items() if d["straggler"]}, key=str),
        }

    def series(self) -> List[Tuple[Optional[Dict[str, str]],
                                   Dict[str, Dict[str, Any]]]]:
        """Per-rank gauge rows for the tracker ``/metrics`` page:
        ``straggler_z`` (worst stage z) and ``straggler_suspect`` (0/1)
        labeled ``rank="N"``."""
        worst: Dict[str, float] = {}
        flagged: Dict[str, bool] = {}
        for per_rank in self.evaluate().values():
            for rank, d in per_rank.items():
                worst[rank] = max(worst.get(rank, float("-inf")), d["z"])
                flagged[rank] = flagged.get(rank, False) or d["straggler"]
        rows: List[Tuple[Optional[Dict[str, str]],
                         Dict[str, Dict[str, Any]]]] = []
        for rank in sorted(worst, key=str):
            rows.append(({"rank": rank}, {
                "straggler_z": {"type": "gauge", "value": worst[rank]},
                "straggler_suspect": {"type": "gauge",
                                      "value": 1 if flagged[rank] else 0},
            }))
        return rows


# ---------------------------------------------------------------------------
# SLO rules
# ---------------------------------------------------------------------------

class SloSpecError(DMLCError):
    """Malformed ``DMLC_SLO_SPEC`` — raised at parse time, loudly: a
    deployment with a typo'd SLO must not silently watch nothing."""


#: default snapshot field tested per metric type
_DEFAULT_FIELD = {"gauge": "value", "counter": "value", "histogram": "p99",
                  "throughput": "windowed_rate", "stage": "mean_sec"}


def _parse_value(text: str) -> float:
    t = text.strip().lower()
    try:
        if t.endswith("ms"):
            return float(t[:-2]) / 1e3
        if t.endswith("s") and not t[:-1].endswith("m"):
            return float(t[:-1])
        return float(t)
    except ValueError:
        raise SloSpecError(f"bad value {text!r}") from None


class SloRule:
    """One compiled rule: ``metric[.field]`` compared against a bound."""

    __slots__ = ("metric", "field", "max_v", "min_v", "for_count", "_hits")

    def __init__(self, metric: str, field: Optional[str], max_v: Optional[float],
                 min_v: Optional[float], for_count: int) -> None:
        self.metric = metric
        self.field = field          # None = resolve from the metric type
        self.max_v = max_v
        self.min_v = min_v
        self.for_count = max(1, int(for_count))
        self._hits = 0              # consecutive breached evaluations

    @property
    def name(self) -> str:
        parts = [self.metric]
        if self.field:
            parts.append(f"field={self.field}")
        if self.max_v is not None:
            parts.append(f"max={self.max_v:g}")
        if self.min_v is not None:
            parts.append(f"min={self.min_v:g}")
        return ":".join(parts)

    def check(self, snapshot: Dict[str, Dict[str, Any]]
              ) -> Optional[Dict[str, Any]]:
        """Evaluate against one snapshot; a firing breach (consecutive
        count reached) returns its description dict, else None.  A metric
        absent from the snapshot is not a breach — the workload that
        would populate it simply hasn't run."""
        snap = snapshot.get(self.metric)
        if not isinstance(snap, dict):
            self._hits = 0
            return None
        field = self.field or _DEFAULT_FIELD.get(snap.get("type"), "value")
        v = snap.get(field)
        if not isinstance(v, (int, float)):
            self._hits = 0
            return None
        breached = ((self.max_v is not None and v > self.max_v)
                    or (self.min_v is not None and v < self.min_v))
        if not breached:
            self._hits = 0
            return None
        self._hits += 1
        if self._hits < self.for_count:
            return None
        return {"rule": self.name, "metric": self.metric, "field": field,
                "value": float(v), "max": self.max_v, "min": self.min_v,
                "consecutive": self._hits}


def parse_slo_spec(spec: str) -> List[SloRule]:
    """Compile a ``DMLC_SLO_SPEC`` string (grammar in the module doc)."""
    rules: List[SloRule] = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        metric = parts[0].strip()
        if not metric:
            raise SloSpecError(f"clause {clause!r} has no metric name")
        kv: Dict[str, str] = {}
        for p in parts[1:]:
            if "=" not in p:
                raise SloSpecError(f"bad key=value {p!r} in {clause!r}")
            k, v = p.split("=", 1)
            kv[k.strip()] = v.strip()
        unknown = set(kv) - {"max", "min", "field", "for"}
        if unknown:
            raise SloSpecError(
                f"unknown keys {sorted(unknown)} in clause {clause!r}")
        if "max" not in kv and "min" not in kv:
            raise SloSpecError(f"clause {clause!r} has neither max nor min")
        try:
            rules.append(SloRule(
                metric,
                field=kv.get("field"),
                max_v=_parse_value(kv["max"]) if "max" in kv else None,
                min_v=_parse_value(kv["min"]) if "min" in kv else None,
                for_count=int(kv.get("for", 1))))
        except ValueError as e:
            raise SloSpecError(f"bad value in clause {clause!r}: {e}") \
                from None
    if not rules:
        raise SloSpecError(f"empty SLO spec {spec!r}")
    return rules


#: the spec the most recently constructed monitor runs (incident metadata)
_active_spec: Optional[str] = None


def active_slo_spec() -> Optional[str]:
    return _active_spec


class SloMonitor:
    """Periodic SLO evaluation over a registry.

    One daemon thread snapshots the registry every ``interval_s``
    (``DMLC_SLO_INTERVAL``), checks every rule, and on a firing breach:
    bumps ``slo.breaches``, holds ``slo.active_breaches`` at the live
    breach count (the serving health property degrades on > 0), logs,
    and triggers a flight-recorder dump naming the rule.  Each tick also
    feeds the flight recorder's metric-snapshot ring, so an incident
    bundle carries the before/after delta.
    """

    def __init__(self, rules: List[SloRule],
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: Optional[float] = None,
                 spec: Optional[str] = None,
                 on_breach: Optional[Callable[[Dict[str, Any]], None]]
                 = None) -> None:
        global _active_spec
        self.rules = list(rules)
        self.registry = registry if registry is not None else metrics
        if interval_s is None:
            interval_s = get_env("DMLC_SLO_INTERVAL", 5.0)
        self.interval_s = float(interval_s)
        self.spec = spec
        self.on_breach = on_breach
        self.breaches: List[Dict[str, Any]] = []   # most recent firing set
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _active_spec = spec

    def evaluate_once(self) -> List[Dict[str, Any]]:
        """One evaluation pass (what the thread runs; tests call it
        directly for determinism).  Returns the breaches that FIRED."""
        snapshot = self.registry.snapshot()
        fl = _flight_mod()
        if fl is not None:
            fl.flight_recorder.note_snapshot(registry=self.registry)
        fired = [b for b in (rule.check(snapshot) for rule in self.rules)
                 if b is not None]
        fired.extend(self._extra_checks(snapshot))
        self.registry.gauge("slo.active_breaches").set(len(fired))
        if fired:
            self.breaches = fired
            self.registry.counter("slo.breaches").add(len(fired))
            for b in fired:
                log_warning("SLO breach: %s observed %.6g", b["rule"],
                            b["value"])
                if self.on_breach is not None:
                    self.on_breach(b)
                if fl is not None:
                    fl.flight_recorder.note("slo_breach", **{
                        k: v for k, v in b.items() if v is not None})
            # auto-diagnosis (r20) BEFORE the dump, so the bundle's
            # diagnosis.json is the breach-scoped verdict, not a generic
            # window (lazy import: diagnose imports this module's
            # StreamingStat; the edge must stay one-way at import time)
            try:
                from . import diagnose as _diagnose
                _diagnose.on_breach(fired[0])
            except Exception as e:  # noqa: BLE001 — diagnosis must
                # never block the incident dump it decorates
                log_warning("breach diagnosis failed: %s", e)
            if fl is not None:
                fl.dump_incident("slo_breach", registry=self.registry,
                                 breaches=fired)
        return fired

    def _extra_checks(self, snapshot: Dict[str, Any]
                      ) -> List[Dict[str, Any]]:
        """Hook for subclasses adding non-snapshot checks (the burn-rate
        monitor in :mod:`~dmlc_core_tpu.telemetry.slo` evaluates its
        rules against the history store here)."""
        return []

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception as e:  # noqa: BLE001 — the watchdog must
                # outlive any single bad evaluation
                log_warning("SLO monitor evaluation failed: %s", e)

    def start(self) -> "SloMonitor":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="dmlc-slo", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


#: the monitor maybe_monitor_from_env started, so repeated env
#: activations (server + exporter both calling it) reuse one thread
_env_monitor: Optional[SloMonitor] = None


def maybe_monitor_from_env(registry: Optional[MetricsRegistry] = None,
                           autostart: bool = True) -> Optional[SloMonitor]:
    """Build (and by default start) an :class:`SloMonitor` when
    ``DMLC_SLO_SPEC`` is set.  Unset → None, exact no-op — matching the
    ``DMLC_FAULT_SPEC`` convention.  Malformed specs raise loudly.
    Idempotent per spec value: a second call while the same spec's
    monitor is live returns it instead of stacking threads."""
    global _env_monitor
    import os
    spec = get_env(SLO_ENV_VAR, None) or None
    if not spec:
        return None
    if (_env_monitor is not None and _env_monitor.spec == spec
            and _env_monitor._thread is not None):
        return _env_monitor
    # route through the superset grammar: clauses with budget= become
    # burn-rate rules over the history store (telemetry.slo), plain
    # clauses behave exactly as before
    from . import slo as _slo
    plain, burn = _slo.parse_slo_spec(spec)
    if burn:
        mon: SloMonitor = _slo.BurnRateMonitor(plain, burn,
                                               registry=registry, spec=spec)
    else:
        mon = SloMonitor(plain, registry=registry, spec=spec)
    _env_monitor = mon
    return mon.start() if autostart else mon
