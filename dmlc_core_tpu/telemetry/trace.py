"""Trace context propagation + in-process span recording.

``utils.metrics`` answers *how much / how fast*; this module answers
*where did this request go*.  A :class:`TraceContext` is a pair of ids
(``trace_id`` for the whole request tree, ``span_id`` for the current
operation) carried in a ``contextvars.ContextVar`` so it follows the
logical call chain — including across ``with``-scoped helper layers —
without threading an argument through every signature.  Crossing a
thread or a wire is explicit: pack ``current()`` ids into the message
(the serving protocol carries them in the request header) and
:func:`activate` the reconstructed context on the other side.

Finished spans land in a process-global lock-protected ring buffer
(:class:`SpanRecorder`): bounded memory, newest-wins, cheap enough for
per-request recording.  Consumers are ``telemetry.chrome_trace``
(Perfetto export) and the ``/spans`` endpoint of
``telemetry.exposition``.

Usage::

    with span("serving.client.predict", rows=4):        # scoped span
        ...                                             # children nest

    s = start_span("serving.server.request", parent=ctx)  # manual span
    ...                                                   # (async paths)
    s.end(status="OK")

    add_event("retry", attempt=2)   # annotate the active span, if any
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, Union

from ..utils.metrics import metrics
from ..utils.parameter import get_env

__all__ = [
    "TraceContext", "Span", "SpanRecorder", "recorder", "current",
    "current_trace_id", "new_trace_id", "start_span", "span", "activate",
    "add_event", "format_id", "wire_ids", "from_wire", "set_sampler",
    "get_sampler",
]


class TraceContext(NamedTuple):
    """Wire-portable identity of an in-progress span: 64-bit non-zero
    ``trace_id`` shared by every span of one request tree, plus the
    ``span_id`` new children must name as their parent."""

    trace_id: int
    span_id: int


def format_id(v: int) -> str:
    """Canonical hex rendering (what logs/exports show)."""
    return f"{v & 0xFFFFFFFFFFFFFFFF:016x}"


# one RNG for id generation; os.urandom-seeded so forked workers diverge
_id_rng = random.Random(int.from_bytes(os.urandom(8), "little"))
_id_lock = threading.Lock()


def new_trace_id() -> int:
    """Random non-zero 63-bit id (zero is the wire's 'untraced' marker;
    bit 63 is reserved as the tail-sampling ``debug=1`` force-keep flag
    — see ``telemetry.sampling`` — so it is never minted by accident)."""
    with _id_lock:
        return _id_rng.randrange(1, 1 << 63)


class SpanRecorder:
    """Lock-protected ring buffer of finished span/event records.

    Records are plain JSON-ready dicts (see :meth:`Span.end` for the
    schema) so exports never touch live objects.  Bounded by
    ``capacity`` (env ``DMLC_SPAN_BUFFER``): under sustained load old
    spans fall off the back — observability must never become the
    memory leak it exists to find.
    """

    def __init__(self, capacity: int = 4096) -> None:
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(1, int(capacity)))
        self._dropped = 0

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            evicted = len(self._buf) == self._buf.maxlen
            if evicted:
                self._dropped += 1
            self._buf.append(rec)
        if evicted:
            # eviction at maxlen used to be invisible — consumers of a
            # lossy /spans window must be able to see that it is lossy
            metrics.counter("telemetry.spans_dropped").add(1)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    @property
    def dropped(self) -> int:
        """Records evicted by the ring since construction/clear()."""
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


#: process-global recorder (the /spans endpoint and Chrome export read it)
recorder = SpanRecorder(capacity=get_env("DMLC_SPAN_BUFFER", 4096))

# Optional tail sampler (telemetry.sampling.TailSampler) interposed
# between span completion and the recorder.  None (the default) keeps
# the record-everything behaviour; ``sampling.install()`` swaps it in.
# This module stays import-light — it never imports sampling itself.
_sampler: Optional[Any] = None


def set_sampler(sampler: Optional[Any]) -> None:
    """Install (or with None, remove) the tail-sampling hook.  The
    sampler must expose ``on_start(trace_id)``, ``on_end(trace_id,
    rec)`` and ``on_event(trace_id_or_none, rec)``."""
    global _sampler
    _sampler = sampler


def get_sampler() -> Optional[Any]:
    return _sampler

# The active node of the logical call chain: a live Span in-process, or a
# bare TraceContext re-activated after crossing a thread/wire boundary.
_current: contextvars.ContextVar[Optional[Union["Span", TraceContext]]] = \
    contextvars.ContextVar("dmlc_trace", default=None)


def _ids_of(node: Union["Span", TraceContext, None]) -> Optional[TraceContext]:
    if node is None:
        return None
    if isinstance(node, TraceContext):
        return node
    return node.context


def current() -> Optional[TraceContext]:
    """The active trace context (ids only), or None when untraced."""
    return _ids_of(_current.get())


def current_trace_id() -> Optional[str]:
    """Hex trace id of the active context (log-correlation helper)."""
    ctx = current()
    return format_id(ctx.trace_id) if ctx is not None else None


def wire_ids() -> "tuple[int, int]":
    """``(trace_id, span_id)`` of the active context for wire injection;
    ``(0, 0)`` when untraced — zero is the wire's 'untraced' marker, so
    senders can pack unconditionally (the serving header convention,
    shared by the data-service JSON RPCs)."""
    ctx = current()
    return (ctx.trace_id, ctx.span_id) if ctx is not None else (0, 0)


def from_wire(trace_id: Any, span_id: Any) -> Optional[TraceContext]:
    """Reconstruct a remote parent from wire ids.  A zero, absent, or
    malformed trace id means the request is untraced → ``None`` (safe to
    hand straight to :func:`activate` / ``start_span(parent=...)``)."""
    try:
        tid, sid = int(trace_id or 0), int(span_id or 0)
    except (TypeError, ValueError):
        return None
    if tid == 0:
        return None
    return TraceContext(tid, sid)


class Span:
    """One timed operation.  Created via :func:`start_span` / :func:`span`;
    finished exactly once with :meth:`end` (idempotent — async completion
    paths may race a cleanup path)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "events", "_t0_wall", "_t0_mono", "_tid", "_thread",
                 "_ended")

    def __init__(self, name: str, trace_id: int, span_id: int,
                 parent_id: Optional[int], attrs: Dict[str, Any]) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.events: List[Dict[str, Any]] = []
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        t = threading.current_thread()
        self._tid = t.ident or 0
        self._thread = t.name
        self._ended = False

    @property
    def context(self) -> TraceContext:
        """What children (local or remote) name as their parent."""
        return TraceContext(self.trace_id, self.span_id)

    def event(self, name: str, **attrs: Any) -> None:
        """Attach a point-in-time annotation (retry, breaker trip, ...)."""
        self.events.append({
            "name": name,
            "ts_us": int(time.time() * 1e6),
            "attrs": _jsonable(attrs),
        })

    def end(self, **attrs: Any) -> None:
        """Finish the span and push its record into the ring buffer."""
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        rec = {
            "kind": "span",
            "name": self.name,
            "trace_id": format_id(self.trace_id),
            "span_id": format_id(self.span_id),
            "parent_id": (format_id(self.parent_id)
                          if self.parent_id else None),
            "ts_us": int(self._t0_wall * 1e6),
            "dur_us": max(0, int((time.monotonic() - self._t0_mono) * 1e6)),
            "pid": os.getpid(),
            "tid": self._tid,
            "thread": self._thread,
            "attrs": _jsonable(self.attrs),
            "events": self.events,
        }
        s = _sampler
        if s is not None:
            s.on_end(self.trace_id, rec)
        else:
            recorder.record(rec)


def _jsonable(attrs: Dict[str, Any]) -> Dict[str, Any]:
    """Attrs must survive json.dumps — coerce exotic values to str."""
    out: Dict[str, Any] = {}
    for k, v in attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            try:
                json.dumps(v)
                out[k] = v
            except (TypeError, ValueError):
                out[k] = str(v)
    return out


def start_span(name: str, parent: Optional[TraceContext] = None,
               **attrs: Any) -> Span:
    """Create a span WITHOUT activating it (async server paths hold the
    object and ``end()`` it from a completion callback).  ``parent``
    defaults to the ambient context; with neither, the span roots a new
    trace."""
    if parent is None:
        parent = current()
    if parent is None:
        trace_id, parent_id = new_trace_id(), None
    else:
        trace_id, parent_id = parent.trace_id, parent.span_id
    s = _sampler
    if s is not None:
        s.on_start(trace_id)
    return Span(name, trace_id, new_trace_id(), parent_id, _jsonable(attrs))


@contextlib.contextmanager
def span(name: str, **attrs: Any) -> Iterator[Span]:
    """Scoped span: child of the ambient context, active for the block,
    ended on exit (exceptions recorded as ``error`` before re-raising)."""
    s = start_span(name, **attrs)
    token = _current.set(s)
    try:
        yield s
    except BaseException as e:
        s.end(error=f"{type(e).__name__}: {e}")
        raise
    finally:
        try:
            _current.reset(token)
        except ValueError:
            # a span opened inside a generator dies wherever the
            # generator is finalized: GC can close an abandoned iterator
            # from another thread's context, where this token is foreign.
            # The span still ends; only the ambient-context pop is moot.
            pass
        s.end()


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Re-enter a context that crossed a thread or wire boundary (ids
    only — the originating span keeps ownership of its record).  ``None``
    is a no-op so call sites need no branching."""
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


def add_event(name: str, **attrs: Any) -> None:
    """Annotate the active span; with only a re-activated context (or no
    trace at all) record a standalone instant event instead, so signals
    like retries are never dropped on untraced paths."""
    node = _current.get()
    if isinstance(node, Span):
        node.event(name, **attrs)
        return
    ctx = _ids_of(node)
    t = threading.current_thread()
    rec = {
        "kind": "event",
        "name": name,
        "trace_id": format_id(ctx.trace_id) if ctx else None,
        "span_id": format_id(ctx.span_id) if ctx else None,
        "ts_us": int(time.time() * 1e6),
        "pid": os.getpid(),
        "tid": t.ident or 0,
        "thread": t.name,
        "attrs": _jsonable(attrs),
    }
    s = _sampler
    if s is not None:
        s.on_event(ctx.trace_id if ctx else None, rec)
    else:
        recorder.record(rec)
