"""Critical-path analytics over the span ring: where did the p99 go.

The recorder (``telemetry.trace``) already holds the last few thousand
span records with parent links; Perfetto can *show* one trace, but
"which stage actually bounds the slow requests" needed a human staring
at timelines.  This module answers it mechanically:

* :func:`assemble` — span records → per-trace trees (a span whose
  parent scrolled off the ring roots its own subtree, so eviction
  degrades coverage, never correctness);
* :func:`critical_path` — the classic backward walk: from the end of a
  span, repeatedly step into the latest-finishing child that ends
  before the cursor; the gaps are the span's **self time**.  The sum of
  segment self-times equals the root's duration, so the breakdown is a
  complete accounting, not a sample;
* :func:`analyze` — the ``top=N`` slowest roots, each with its path
  breakdown, plus self-time aggregated by span name across those
  requests — the "client vs wire vs batcher vs engine vs h2d" answer
  as one dict.

Every ``TelemetryServer`` serves :func:`analyze` at ``/analyze?top=N``
(``format=text`` renders :func:`render_text`); flight bundles attach
the same breakdown as ``critical_path.txt``
(``DMLC_FLIGHT_CRITICAL_TOP`` roots, default 5).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.parameter import get_env
from . import trace as _trace

__all__ = ["assemble", "critical_path", "analyze", "render_text",
           "ANALYZE_SCHEMA"]

ANALYZE_SCHEMA = "dmlc.telemetry.critical_path/1"


class _Node:
    __slots__ = ("rec", "children")

    def __init__(self, rec: Dict[str, Any]) -> None:
        self.rec = rec
        self.children: List["_Node"] = []

    @property
    def start(self) -> int:
        return int(self.rec.get("ts_us", 0))

    @property
    def end(self) -> int:
        return self.start + int(self.rec.get("dur_us", 0))

    @property
    def name(self) -> str:
        return str(self.rec.get("name", "?"))


def assemble(records: Optional[List[Dict[str, Any]]] = None
             ) -> Dict[str, List[_Node]]:
    """Span records → ``{trace_id: [root nodes]}``.  A span whose parent
    is absent (genuinely a root, or its parent was evicted from the
    ring) becomes a root of its own subtree."""
    if records is None:
        records = _trace.recorder.snapshot()
    by_trace: Dict[str, Dict[str, _Node]] = {}
    for rec in records:
        if rec.get("kind") != "span":
            continue
        tid, sid = rec.get("trace_id"), rec.get("span_id")
        if not tid or not sid:
            continue
        by_trace.setdefault(str(tid), {})[str(sid)] = _Node(rec)
    roots: Dict[str, List[_Node]] = {}
    for tid, nodes in by_trace.items():
        tr_roots: List[_Node] = []
        for node in nodes.values():
            parent = nodes.get(str(node.rec.get("parent_id") or ""))
            if parent is not None and parent is not node:
                parent.children.append(node)
            else:
                tr_roots.append(node)
        roots[tid] = tr_roots
    return roots


def critical_path(root: _Node) -> List[Tuple[str, int]]:
    """``[(span_name, self_us), ...]`` along the critical path.

    Backward walk from the root's end: step into the latest-finishing
    child that ends at or before the cursor, charge the gap to the
    current span, recurse; concurrent siblings off the path are by
    definition not what bounded the request.  Malformed timestamps
    (clock steps) clamp to zero rather than emitting negative time.
    """
    segments: List[Tuple[str, int]] = []

    def walk(node: _Node, lo: int, hi: int) -> None:
        cursor = hi
        for child in sorted(node.children, key=lambda n: n.end,
                            reverse=True):
            if child.end > cursor or child.end <= lo:
                continue        # overlaps a later child / outside window
            gap = cursor - child.end
            if gap > 0:
                segments.append((node.name, gap))
            walk(child, max(lo, child.start), child.end)
            cursor = max(lo, child.start)
        if cursor > lo:
            segments.append((node.name, cursor - lo))

    walk(root, root.start, root.end)
    segments.reverse()          # chronological: first gap first
    return segments


def analyze(top: int = 5,
            records: Optional[List[Dict[str, Any]]] = None
            ) -> Dict[str, Any]:
    """The ``/analyze`` document: top-N slowest traces with per-request
    critical paths, plus self-time totals by span name across them."""
    top = max(1, min(int(top), 50))
    roots = assemble(records)
    # one "request" per trace: its longest root
    requests: List[Tuple[str, _Node]] = []
    for tid, rs in roots.items():
        if rs:
            requests.append((tid, max(rs, key=lambda n: n.end - n.start)))
    requests.sort(key=lambda t: t[1].end - t[1].start, reverse=True)
    picked = requests[:top]
    self_time: Dict[str, int] = {}
    out: List[Dict[str, Any]] = []
    for tid, root in picked:
        path = critical_path(root)
        dur = max(1, root.end - root.start)
        for name, us in path:
            self_time[name] = self_time.get(name, 0) + us
        out.append({
            "trace_id": tid,
            "root": root.name,
            "dur_us": root.end - root.start,
            "path": [{"name": n, "self_us": us,
                      "pct": round(100.0 * us / dur, 1)}
                     for n, us in path],
        })
    return {"schema": ANALYZE_SCHEMA, "ts": time.time(),
            "traces_seen": len(roots), "top": out,
            "self_time_us": dict(sorted(self_time.items(),
                                        key=lambda kv: -kv[1]))}


def render_text(doc: Dict[str, Any]) -> str:
    """``/analyze?format=text`` / ``critical_path.txt``: the aggregate
    self-time table first (the headline), then each slow trace's path."""
    lines: List[str] = []
    agg = doc.get("self_time_us") or {}
    total = sum(agg.values()) or 1
    lines.append(f"critical path over top {len(doc.get('top', []))} of "
                 f"{doc.get('traces_seen', 0)} trace(s)")
    lines.append("self time by span:")
    for name, us in agg.items():
        lines.append(f"  {name:<40} {us / 1e3:>10.3f} ms "
                     f"{100.0 * us / total:>5.1f}%")
    for tr in doc.get("top", []):
        lines.append(f"trace {tr['trace_id']} root={tr['root']} "
                     f"{tr['dur_us'] / 1e3:.3f} ms")
        for seg in tr["path"]:
            lines.append(f"  {seg['name']:<40} {seg['self_us'] / 1e3:>10.3f}"
                         f" ms {seg['pct']:>5.1f}%")
    return "\n".join(lines) + "\n"


def incident_breakdown() -> str:
    """The flight-recorder hook: the top-N breakdown as text, empty when
    the ring holds no complete spans (the bundle then skips the file)."""
    top = int(get_env("DMLC_FLIGHT_CRITICAL_TOP", 5))
    doc = analyze(top=top)
    if not doc["top"]:
        return ""
    return render_text(doc)
