"""Stdlib sampling stack profiler — the incident-time "what is every
thread doing" answer, with zero dependencies and zero cost when idle.

``py-spy``/``perf`` cannot be assumed on a TPU worker image, and cProfile
is a tracing profiler: its per-call hook is far too heavy to leave armed
in a serving or ingest hot loop.  A *sampling* profiler pays only at the
sample clock: a daemon thread wakes at ``DMLC_PROFILE_HZ`` (default 67 —
deliberately co-prime with 10 ms scheduler ticks so samples do not beat
against the interpreter's own switch interval), snapshots every thread's
stack via :func:`sys._current_frames`, and folds each stack into
collapsed form (``mod:func;mod:func <count>`` — the flamegraph.pl /
speedscope interchange format), so a profile window is a text blob small
enough to ride inside an incident bundle.

Three entry points, by audience:

* :class:`SamplingProfiler` — own the window yourself (tests, long
  experiments): ``start()`` / ``stop()`` / ``collapsed()``.
* :func:`profile_for` — one bounded window, returns the collapsed text;
  this is what a ``TelemetryServer`` mounts at ``/profile?seconds=N``
  (the HTTP thread blocks for the window; the server is threading, so
  concurrent scrapes still get /metrics).
* :func:`incident_profile` — the flight-recorder hook: a short window
  (``DMLC_FLIGHT_PROFILE_S``, default 0.25 s) captured *inside*
  ``bundle()`` so every stall/SLO incident carries the stacks that were
  running when the trigger fired, not a reconstruction after the fact.

Sampler accounting lands in ``utils.metrics`` (``profile.samples``) so a
forgotten always-on profiler is visible in any snapshot.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Optional

from ..utils.metrics import metrics
from ..utils.parameter import get_env

__all__ = ["SamplingProfiler", "profile_for", "incident_profile",
           "diff_collapsed", "record_baseline", "baseline",
           "incident_profile_diff"]

#: default sample rate; co-prime with common 10 ms scheduler quanta
_DEFAULT_HZ = 67.0
#: hard bounds on a /profile window — a scrape must not pin an HTTP
#: thread for minutes, and a sub-10ms window cannot hold even one sample
_MIN_WINDOW_S = 0.05
_MAX_WINDOW_S = 60.0


def _frame_label(frame) -> str:
    """``module:function`` — stable across hosts (no absolute paths), the
    granularity flamegraphs aggregate well at."""
    code = frame.f_code
    mod = frame.f_globals.get("__name__", "?")
    return f"{mod}:{code.co_name}"


class SamplingProfiler:
    """Fold ``sys._current_frames`` samples into collapsed stacks.

    Thread-safe; one sampler thread per instance.  Stacks are keyed
    root-first (outermost frame leftmost), matching what flamegraph
    tooling expects.  ``max_stacks`` bounds the fold table so a pathological
    workload (e.g. generated code with unbounded distinct frames) cannot
    grow memory without bound — overflow folds into a sentinel bucket.
    """

    def __init__(self, hz: Optional[float] = None,
                 max_stacks: int = 10000) -> None:
        if hz is None:
            hz = get_env("DMLC_PROFILE_HZ", _DEFAULT_HZ)
        self.hz = max(1.0, min(1000.0, float(hz)))
        self.max_stacks = int(max_stacks)
        self._counts: Dict[str, int] = {}
        self._samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="dmlc-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- sampling --

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            self.sample_once(skip_tid=me)

    def sample_once(self, skip_tid: Optional[int] = None) -> None:
        """Take one sample of every live thread (public for tests: a
        deterministic single sample without the wall-clock loop)."""
        frames = sys._current_frames()
        folded = []
        for tid, frame in frames.items():
            if tid == skip_tid:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                parts.append(_frame_label(f))
                f = f.f_back
                depth += 1
            parts.reverse()
            folded.append(";".join(parts))
        del frames
        with self._lock:
            self._samples += len(folded)
            for stack in folded:
                if stack not in self._counts \
                        and len(self._counts) >= self.max_stacks:
                    stack = "<overflow>"
                self._counts[stack] = self._counts.get(stack, 0) + 1
        metrics.counter("profile.samples").add(len(folded))

    # -- output --

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def collapsed(self) -> str:
        """Collapsed-stack text: one ``stack count`` line per distinct
        stack, heaviest first — feed directly to flamegraph.pl or paste
        into speedscope."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{stack} {n}" for stack, n in items)


def profile_for(seconds: float, hz: Optional[float] = None) -> str:
    """Blocking bounded window → collapsed-stack text (the ``/profile``
    endpoint body).  The window is clamped to [0.05, 60] s: an HTTP
    scrape must terminate, and a shorter window cannot hold a sample."""
    seconds = max(_MIN_WINDOW_S, min(_MAX_WINDOW_S, float(seconds)))
    prof = SamplingProfiler(hz=hz)
    with prof:
        time.sleep(seconds)
    # a very short window on a quiet interpreter can miss the clock
    # entirely; one explicit sample guarantees non-empty output
    if prof.samples == 0:
        prof.sample_once()
    return prof.collapsed()


def incident_profile() -> str:
    """The flight-recorder attachment: one short window sampled at
    incident time (``DMLC_FLIGHT_PROFILE_S``, default 0.25 s — long
    enough for ~16 samples at the default rate, short enough that
    ``bundle()`` stays interactive)."""
    window = get_env("DMLC_FLIGHT_PROFILE_S", 0.25)
    if window <= 0:       # explicit opt-out: profiling disabled
        return ""
    return profile_for(window)


# ---------------------------------------------------------------------------
# profile diffing (r20): incident window vs pre-incident baseline
# ---------------------------------------------------------------------------

def _parse_collapsed(text: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, n = line.rpartition(" ")
        try:
            out[stack] = out.get(stack, 0) + int(n)
        except ValueError:
            continue              # not a collapsed line; ignore
    return out


def diff_collapsed(baseline: str, incident: str) -> str:
    """Differential flamegraph input: the incident profile's share shift
    per stack vs a baseline profile, as annotated collapsed text.

    Both inputs are normalized to *shares* (sample counts divided by the
    profile's total) so windows of different lengths compare honestly.
    One line per stack, largest share growth first::

        <stack> <incident_count> +12.3% (baseline 4.1% -> incident 16.4%)

    Stacks that shrank or vanished follow, prefixed the same way with a
    negative delta — a regression diff must show both what grew and what
    it displaced.  Empty baseline → the incident profile is returned
    annotated as ``(no baseline)`` so callers can always attach *something*.
    """
    inc = _parse_collapsed(incident)
    base = _parse_collapsed(baseline)
    if not base:
        return "\n".join(f"{s} {n} (no baseline)"
                         for s, n in sorted(inc.items(),
                                            key=lambda kv: (-kv[1], kv[0])))
    tot_i = sum(inc.values()) or 1
    tot_b = sum(base.values()) or 1
    rows = []
    for stack in set(inc) | set(base):
        si = inc.get(stack, 0) / tot_i
        sb = base.get(stack, 0) / tot_b
        rows.append((si - sb, stack, inc.get(stack, 0), sb, si))
    rows.sort(key=lambda r: (-r[0], r[1]))
    return "\n".join(
        f"{stack} {n} {d * 100:+.1f}% "
        f"(baseline {sb * 100:.1f}% -> incident {si * 100:.1f}%)"
        for d, stack, n, sb, si in rows)


#: (collapsed_text, unix_ts) of the last healthy-window profile —
#: recorded by plain ``/profile`` scrapes, consumed by ``?diff=1`` and
#: flight bundles
_baseline_lock = threading.Lock()
_baseline: Optional[tuple] = None


def record_baseline(text: str, ts: Optional[float] = None) -> None:
    """Keep ``text`` as the pre-incident baseline profile.  Every plain
    ``/profile`` scrape calls this, so any periodic profile collection
    (cron scrape, dashboard) automatically arms the diff."""
    global _baseline
    if not text:
        return
    with _baseline_lock:
        _baseline = (text, time.time() if ts is None else float(ts))


def baseline() -> Optional[tuple]:
    """The ``(collapsed_text, unix_ts)`` baseline, or None."""
    with _baseline_lock:
        return _baseline


def incident_profile_diff(incident: str) -> str:
    """``profile_diff.txt`` for a flight bundle: the incident window
    diffed against the recorded baseline; "" when no baseline exists
    (the bundle then simply omits the file)."""
    got = baseline()
    if got is None or not incident:
        return ""
    base_text, base_ts = got
    head = (f"# profile diff: baseline @ {base_ts:.0f} "
            f"({time.time() - base_ts:.0f}s ago) vs incident window\n")
    return head + diff_collapsed(base_text, incident)
