"""Stdlib sampling stack profiler — the incident-time "what is every
thread doing" answer, with zero dependencies and zero cost when idle.

``py-spy``/``perf`` cannot be assumed on a TPU worker image, and cProfile
is a tracing profiler: its per-call hook is far too heavy to leave armed
in a serving or ingest hot loop.  A *sampling* profiler pays only at the
sample clock: a daemon thread wakes at ``DMLC_PROFILE_HZ`` (default 67 —
deliberately co-prime with 10 ms scheduler ticks so samples do not beat
against the interpreter's own switch interval), snapshots every thread's
stack via :func:`sys._current_frames`, and folds each stack into
collapsed form (``mod:func;mod:func <count>`` — the flamegraph.pl /
speedscope interchange format), so a profile window is a text blob small
enough to ride inside an incident bundle.

Three entry points, by audience:

* :class:`SamplingProfiler` — own the window yourself (tests, long
  experiments): ``start()`` / ``stop()`` / ``collapsed()``.
* :func:`profile_for` — one bounded window, returns the collapsed text;
  this is what a ``TelemetryServer`` mounts at ``/profile?seconds=N``
  (the HTTP thread blocks for the window; the server is threading, so
  concurrent scrapes still get /metrics).
* :func:`incident_profile` — the flight-recorder hook: a short window
  (``DMLC_FLIGHT_PROFILE_S``, default 0.25 s) captured *inside*
  ``bundle()`` so every stall/SLO incident carries the stacks that were
  running when the trigger fired, not a reconstruction after the fact.

Sampler accounting lands in ``utils.metrics`` (``profile.samples``) so a
forgotten always-on profiler is visible in any snapshot.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Optional

from ..utils.metrics import metrics
from ..utils.parameter import get_env

__all__ = ["SamplingProfiler", "profile_for", "incident_profile"]

#: default sample rate; co-prime with common 10 ms scheduler quanta
_DEFAULT_HZ = 67.0
#: hard bounds on a /profile window — a scrape must not pin an HTTP
#: thread for minutes, and a sub-10ms window cannot hold even one sample
_MIN_WINDOW_S = 0.05
_MAX_WINDOW_S = 60.0


def _frame_label(frame) -> str:
    """``module:function`` — stable across hosts (no absolute paths), the
    granularity flamegraphs aggregate well at."""
    code = frame.f_code
    mod = frame.f_globals.get("__name__", "?")
    return f"{mod}:{code.co_name}"


class SamplingProfiler:
    """Fold ``sys._current_frames`` samples into collapsed stacks.

    Thread-safe; one sampler thread per instance.  Stacks are keyed
    root-first (outermost frame leftmost), matching what flamegraph
    tooling expects.  ``max_stacks`` bounds the fold table so a pathological
    workload (e.g. generated code with unbounded distinct frames) cannot
    grow memory without bound — overflow folds into a sentinel bucket.
    """

    def __init__(self, hz: Optional[float] = None,
                 max_stacks: int = 10000) -> None:
        if hz is None:
            hz = get_env("DMLC_PROFILE_HZ", _DEFAULT_HZ)
        self.hz = max(1.0, min(1000.0, float(hz)))
        self.max_stacks = int(max_stacks)
        self._counts: Dict[str, int] = {}
        self._samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="dmlc-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- sampling --

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        me = threading.get_ident()
        while not self._stop.wait(interval):
            self.sample_once(skip_tid=me)

    def sample_once(self, skip_tid: Optional[int] = None) -> None:
        """Take one sample of every live thread (public for tests: a
        deterministic single sample without the wall-clock loop)."""
        frames = sys._current_frames()
        folded = []
        for tid, frame in frames.items():
            if tid == skip_tid:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < 128:
                parts.append(_frame_label(f))
                f = f.f_back
                depth += 1
            parts.reverse()
            folded.append(";".join(parts))
        del frames
        with self._lock:
            self._samples += len(folded)
            for stack in folded:
                if stack not in self._counts \
                        and len(self._counts) >= self.max_stacks:
                    stack = "<overflow>"
                self._counts[stack] = self._counts.get(stack, 0) + 1
        metrics.counter("profile.samples").add(len(folded))

    # -- output --

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def collapsed(self) -> str:
        """Collapsed-stack text: one ``stack count`` line per distinct
        stack, heaviest first — feed directly to flamegraph.pl or paste
        into speedscope."""
        with self._lock:
            items = sorted(self._counts.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return "\n".join(f"{stack} {n}" for stack, n in items)


def profile_for(seconds: float, hz: Optional[float] = None) -> str:
    """Blocking bounded window → collapsed-stack text (the ``/profile``
    endpoint body).  The window is clamped to [0.05, 60] s: an HTTP
    scrape must terminate, and a shorter window cannot hold a sample."""
    seconds = max(_MIN_WINDOW_S, min(_MAX_WINDOW_S, float(seconds)))
    prof = SamplingProfiler(hz=hz)
    with prof:
        time.sleep(seconds)
    # a very short window on a quiet interpreter can miss the clock
    # entirely; one explicit sample guarantees non-empty output
    if prof.samples == 0:
        prof.sample_once()
    return prof.collapsed()


def incident_profile() -> str:
    """The flight-recorder attachment: one short window sampled at
    incident time (``DMLC_FLIGHT_PROFILE_S``, default 0.25 s — long
    enough for ~16 samples at the default rate, short enough that
    ``bundle()`` stays interactive)."""
    window = get_env("DMLC_FLIGHT_PROFILE_S", 0.25)
    if window <= 0:       # explicit opt-out: profiling disabled
        return ""
    return profile_for(window)
