"""Error budgets + multi-window burn-rate SLO rules over the store.

PR 5's ``DMLC_SLO_SPEC`` judges one snapshot at a time: ``for=N`` is a
consecutive-sample debounce, not an objective.  This module upgrades
the same grammar to Google-SRE-style **error budgets**: a clause that
carries ``budget=`` becomes a burn-rate rule evaluated against the
:mod:`~dmlc_core_tpu.telemetry.timeseries` history instead of the
instantaneous snapshot::

    rule  := metric (':' key '=' value)*

    keys (superset of the PR 5 grammar — old specs parse unchanged):
      max=V / min=V   the per-sample objective ("a good sample keeps
                      p99 under 50ms"); ms/s suffixes as before
      field=F         snapshot field (defaults by type, as before)
      for=N           plain-rule debounce (burn rules ignore it)
      budget=F        error budget as a fraction of samples allowed to
                      violate the objective (e.g. 0.01); presence makes
                      the clause a burn-rate rule
      fast=W/R        fast-burn window and rate: fire at severity
                      "fast" when the bad-sample fraction over the last
                      W (ms/s/m/h suffixes) reaches R × budget AND the
                      latest sample is still bad (the still-burning
                      check standing in for the companion short window
                      at our second-scale horizons).  Default 60s/14.
      slow=W/R        slow-burn window and rate (no still-burning
                      requirement — a sustained simmer should page even
                      between flare-ups).  Default 10m/6.

Example::

    DMLC_SLO_SPEC='serving.latency_s:field=p99:max=50ms:budget=0.02:fast=30s/14:slow=5m/6'

A firing burn rule feeds the same machinery as a plain breach — bumps
``slo.breaches``, holds ``slo.active_breaches`` (``/healthz`` degrades
on > 0), notes + dumps to the flight recorder — and the bundle carries
the surrounding timeline slice (``timeline.json``) so the breach
window rides with the evidence.

:func:`~dmlc_core_tpu.telemetry.anomaly.maybe_monitor_from_env` routes
through :func:`parse_slo_spec` here, so any process that sets
``DMLC_SLO_SPEC`` gets burn-rate support without new wiring.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from .anomaly import SloMonitor, SloRule, SloSpecError, _parse_value
from . import timeseries as _timeseries

__all__ = ["BurnRateRule", "BurnRateMonitor", "parse_slo_spec",
           "parse_duration"]

_DUR_SUFFIX = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(text: str) -> float:
    """``"30s"``/``"5m"``/``"250ms"``/``"1h"``/bare seconds → seconds."""
    t = text.strip().lower()
    for suffix in ("ms", "s", "m", "h"):
        if t.endswith(suffix) and t[:-len(suffix)]:
            try:
                return float(t[:-len(suffix)]) * _DUR_SUFFIX[suffix]
            except ValueError:
                break
    try:
        return float(t)
    except ValueError:
        raise SloSpecError(f"bad duration {text!r}") from None


def _parse_window(text: str, clause: str) -> Tuple[float, float]:
    """``"30s/14"`` → (30.0, 14.0) — window seconds / burn-rate bound."""
    w, sep, r = text.partition("/")
    if not sep:
        raise SloSpecError(f"window {text!r} in {clause!r} is not "
                           f"WINDOW/RATE (e.g. 30s/14)")
    try:
        rate = float(r)
    except ValueError:
        raise SloSpecError(f"bad burn rate {r!r} in {clause!r}") from None
    window = parse_duration(w)
    if window <= 0 or rate <= 0:
        raise SloSpecError(f"window and rate must be positive in {clause!r}")
    return window, rate


class BurnRateRule:
    """One compiled burn-rate clause, evaluated against a history store."""

    __slots__ = ("metric", "field", "max_v", "min_v", "budget",
                 "fast_w", "fast_r", "slow_w", "slow_r")

    def __init__(self, metric: str, field: Optional[str],
                 max_v: Optional[float], min_v: Optional[float],
                 budget: float,
                 fast: Tuple[float, float] = (60.0, 14.0),
                 slow: Tuple[float, float] = (600.0, 6.0)) -> None:
        self.metric = metric
        self.field = field
        self.max_v = max_v
        self.min_v = min_v
        self.budget = float(budget)
        self.fast_w, self.fast_r = fast
        self.slow_w, self.slow_r = slow

    @property
    def name(self) -> str:
        bound = (f"max={self.max_v:g}" if self.max_v is not None
                 else f"min={self.min_v:g}")
        return (f"{self.metric}:{bound}:budget={self.budget:g}"
                f":fast={self.fast_w:g}s/{self.fast_r:g}"
                f":slow={self.slow_w:g}s/{self.slow_r:g}")

    def _bad(self, v: float) -> bool:
        return ((self.max_v is not None and v > self.max_v)
                or (self.min_v is not None and v < self.min_v))

    def _series_name(self, history: "_timeseries.HistoryStore") -> str:
        """Resolve the store series for this clause: ``metric.field``
        when the sampler flattened a field out, bare ``metric`` for
        gauges."""
        field = self.field
        if field is None:
            # without a live snapshot the type is unknown; prefer the
            # flattened candidates the sampler actually produced
            names = set(history.series_names())
            for f in ("p99", "rate", "mean_s", "value"):
                if f"{self.metric}.{f}" in names:
                    return f"{self.metric}.{f}"
            return self.metric
        if field == "value":
            return self.metric
        # the sampler stores histogram p99/p50 and *.rate under dotted
        # names; anything else falls back to the dotted form too
        mapped = {"windowed_rate": "rate", "mean_sec": "mean_s",
                  "count": "rate"}.get(field, field)
        return f"{self.metric}.{mapped}"

    def check(self, history: "_timeseries.HistoryStore",
              now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Evaluate both windows; returns the breach dict of the most
        severe firing window ("fast" over "slow"), else None.  An empty
        window is not a breach — no traffic burns no budget."""
        if now is None:
            now = time.time()
        series = self._series_name(history)
        fired: Optional[Dict[str, Any]] = None
        for severity, window, rate in (("slow", self.slow_w, self.slow_r),
                                       ("fast", self.fast_w, self.fast_r)):
            pts = history.query(series, since=window, now=now)
            if not pts:
                continue
            bad = sum(1 for _ts, v in pts if self._bad(v))
            frac = bad / len(pts)
            burn = frac / self.budget if self.budget > 0 else float("inf")
            if burn < rate:
                continue
            if severity == "fast" and not self._bad(pts[-1][1]):
                continue        # still-burning check (module doc)
            fired = {"rule": self.name, "metric": self.metric,
                     "series": series, "severity": severity,
                     "window_s": window, "burn_rate": round(burn, 3),
                     "burn_threshold": rate, "budget": self.budget,
                     "bad_fraction": round(frac, 4), "samples": len(pts),
                     "value": float(pts[-1][1]),
                     "max": self.max_v, "min": self.min_v}
        return fired


def parse_slo_spec(spec: str) -> Tuple[List[SloRule], List[BurnRateRule]]:
    """Compile a ``DMLC_SLO_SPEC`` into (plain rules, burn rules).
    Strict superset of the PR 5 grammar: clauses without ``budget=``
    compile to the same :class:`SloRule` objects as before."""
    plain: List[SloRule] = []
    burn: List[BurnRateRule] = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        metric = parts[0].strip()
        if not metric:
            raise SloSpecError(f"clause {clause!r} has no metric name")
        kv: Dict[str, str] = {}
        for p in parts[1:]:
            if "=" not in p:
                raise SloSpecError(f"bad key=value {p!r} in {clause!r}")
            k, v = p.split("=", 1)
            kv[k.strip()] = v.strip()
        unknown = set(kv) - {"max", "min", "field", "for",
                             "budget", "fast", "slow"}
        if unknown:
            raise SloSpecError(
                f"unknown keys {sorted(unknown)} in clause {clause!r}")
        if "max" not in kv and "min" not in kv:
            raise SloSpecError(f"clause {clause!r} has neither max nor min")
        max_v = _parse_value(kv["max"]) if "max" in kv else None
        min_v = _parse_value(kv["min"]) if "min" in kv else None
        if "budget" not in kv:
            if "fast" in kv or "slow" in kv:
                raise SloSpecError(
                    f"clause {clause!r} has burn windows but no budget=")
            try:
                plain.append(SloRule(metric, field=kv.get("field"),
                                     max_v=max_v, min_v=min_v,
                                     for_count=int(kv.get("for", 1))))
            except ValueError as e:
                raise SloSpecError(
                    f"bad value in clause {clause!r}: {e}") from None
            continue
        try:
            budget = float(kv["budget"])
        except ValueError:
            raise SloSpecError(
                f"bad budget {kv['budget']!r} in {clause!r}") from None
        if not 0 < budget <= 1:
            raise SloSpecError(
                f"budget must be in (0, 1] in clause {clause!r}")
        burn.append(BurnRateRule(
            metric, field=kv.get("field"), max_v=max_v, min_v=min_v,
            budget=budget,
            fast=_parse_window(kv["fast"], clause) if "fast" in kv
            else (60.0, 14.0),
            slow=_parse_window(kv["slow"], clause) if "slow" in kv
            else (600.0, 6.0)))
    if not plain and not burn:
        raise SloSpecError(f"empty SLO spec {spec!r}")
    return plain, burn


class BurnRateMonitor(SloMonitor):
    """An :class:`SloMonitor` that also evaluates burn-rate rules
    against a history store (the process-global one by default).
    Starting the monitor starts the sampler — a burn rule over an empty
    store would otherwise silently watch nothing."""

    def __init__(self, rules: List[SloRule],
                 burn_rules: List[BurnRateRule],
                 history: Optional["_timeseries.HistoryStore"] = None,
                 **kw: Any) -> None:
        super().__init__(rules, **kw)
        self.burn_rules = list(burn_rules)
        self.history = history if history is not None \
            else _timeseries.history

    def _extra_checks(self, snapshot: Dict[str, Any]
                      ) -> List[Dict[str, Any]]:
        return [b for b in (rule.check(self.history)
                            for rule in self.burn_rules) if b is not None]

    def start(self) -> "BurnRateMonitor":
        if not self.history.running:
            self.history.start()
        super().start()
        return self
