"""Chrome trace-event export of recorded spans (Perfetto-loadable).

Converts :mod:`telemetry.trace` ring-buffer records into the Chrome
Trace Event JSON object format (``{"traceEvents": [...]}``), the
interchange format both ``chrome://tracing`` and https://ui.perfetto.dev
load directly.

Each span record becomes two views of the same data:

* a per-thread complete event (``ph: "X"``) — shows wall-clock nesting
  on the thread that ran the work;
* a nestable async pair (``ph: "b"`` / ``"e"``) keyed by the hex
  ``trace_id`` — Perfetto groups all spans of one request tree onto a
  single async track, which is what makes the cross-process
  client→server→engine nesting visible even though each hop ran on a
  different thread (or machine).

Point events (retries, breaker trips) become instant events
(``ph: "i"``).  Timestamps/durations are microseconds, per the spec.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from . import trace as _trace

__all__ = ["to_chrome_trace", "write_chrome_trace"]


def _args(rec: Dict[str, Any]) -> Dict[str, Any]:
    args = dict(rec.get("attrs") or {})
    for k in ("trace_id", "span_id", "parent_id"):
        if rec.get(k):
            args[k] = rec[k]
    return args


def to_chrome_trace(records: Optional[Sequence[Dict[str, Any]]] = None,
                    ) -> Dict[str, Any]:
    """Render span records (default: the global recorder's snapshot) as a
    Chrome trace-event JSON object."""
    if records is None:
        records = _trace.recorder.snapshot()
    events: List[Dict[str, Any]] = []
    for rec in records:
        pid = rec.get("pid", 0)
        tid = rec.get("tid", 0)
        if rec.get("kind") == "span":
            ts = rec["ts_us"]
            dur = rec.get("dur_us", 0)
            events.append({
                "name": rec["name"], "cat": "span", "ph": "X",
                "ts": ts, "dur": dur, "pid": pid, "tid": tid,
                "args": _args(rec),
            })
            if rec.get("trace_id"):
                # async nestable pair: one track per trace_id in Perfetto
                common = {"name": rec["name"], "cat": "trace",
                          "id": rec["trace_id"], "pid": pid, "tid": tid}
                events.append({**common, "ph": "b", "ts": ts,
                               "args": _args(rec)})
                events.append({**common, "ph": "e", "ts": ts + dur})
            for ev in rec.get("events") or ():
                events.append({
                    "name": ev["name"], "cat": "span_event", "ph": "i",
                    "ts": ev["ts_us"], "pid": pid, "tid": tid, "s": "t",
                    "args": dict(ev.get("attrs") or {}),
                })
        else:  # standalone instant event
            events.append({
                "name": rec["name"], "cat": "event", "ph": "i",
                "ts": rec["ts_us"], "pid": pid, "tid": tid, "s": "p",
                "args": _args(rec),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       records: Optional[Sequence[Dict[str, Any]]] = None,
                       ) -> str:
    """Dump :func:`to_chrome_trace` to ``path``; returns the path."""
    doc = to_chrome_trace(records)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path
