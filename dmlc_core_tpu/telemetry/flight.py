"""Flight recorder: an always-on, bounded black box per process.

A production incident is usually diagnosed from evidence that no longer
exists by the time anyone looks — the spans scrolled off, the logs
rotated, the metrics page shows *now*, not *then*.  This module keeps
the last few minutes of everything in bounded rings and, on trigger,
dumps one **self-contained incident bundle**:

``incident.json``
    schema ``dmlc.flight.incident/1``: the trigger (reason + detail,
    e.g. the breached SLO rule), process identity (pid/host/rank), the
    active ``DMLC_SLO_SPEC`` / ``DMLC_FAULT_SPEC``, the full registry
    snapshot, counter deltas against the oldest ring snapshot, and the
    recorder's note ring (injected faults, SLO breaches, stage stalls,
    retrace alerts).
``trace.json``
    Chrome trace-event JSON of the span ring buffer — drop it on
    https://ui.perfetto.dev and see what the process was doing when it
    died.
``log_tail.txt``
    the last N log lines (``utils.logging``'s in-process tail ring).

Triggers (all funnel into :meth:`FlightRecorder.dump`):

* **SLO breach** — ``telemetry.anomaly.SloMonitor`` dumps with the
  breached rule in the detail.
* **Injected fault** — ``utils/faults.py`` calls :func:`note_fault` on
  every injected error (via ``sys.modules``, no import), so a chaos run
  leaves bundles behind exactly like a real incident would.
* **Fatal signal / unhandled exception** — :meth:`FlightRecorder.install`
  chains onto ``sys.excepthook`` / ``threading.excepthook`` and the
  catchable fatal signals (SIGTERM, SIGABRT).
* **Explicit** — ``GET /flight`` on any exposition server returns the
  bundle inline (and writes it to disk when armed).

The recorder itself is always on — the rings exist regardless — but
writing to disk requires **arming** with a directory (``DMLC_FLIGHT_DIR``
or :meth:`arm`).  Dumps are rate-limited (``DMLC_FLIGHT_MIN_INTERVAL``)
so a breach storm produces one bundle per window, not a disk full.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import get_log_tail, log_info, log_warning
from ..utils.metrics import MetricsRegistry, metrics
from ..utils.parameter import get_env
from . import trace as _trace
from .chrome_trace import to_chrome_trace

__all__ = ["FlightRecorder", "flight_recorder", "dump_incident", "note",
           "note_fault", "maybe_arm_from_env", "register_contributor",
           "unregister_contributor", "INCIDENT_SCHEMA"]

INCIDENT_SCHEMA = "dmlc.flight.incident/1"

#: pluggable bundle sections: name → zero-arg callable returning a
#: JSON-ready value, snapshotted into every bundle under that key.
#: Subsystems owning per-process state the recorder cannot reach register
#: here (the data-service dispatcher contributes its lease ledger); a
#: failing contributor degrades to an error string, never kills the dump.
_contrib_lock = threading.Lock()
_contributors: Dict[str, Callable[[], Any]] = {}


def register_contributor(name: str, fn: Callable[[], Any]) -> None:
    """Attach a named section to every future incident bundle (last
    registration per name wins — a restarted dispatcher re-registers)."""
    with _contrib_lock:
        _contributors[name] = fn


def unregister_contributor(name: str) -> None:
    with _contrib_lock:
        _contributors.pop(name, None)


def _counter_deltas(old: Dict[str, Dict[str, Any]],
                    new: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """What moved between two snapshots: counter/throughput totals and
    stage count/total deltas.  Gauges and quantiles are point-in-time —
    both endpoints already ride the bundle."""
    out: Dict[str, Any] = {}
    for name, snap in new.items():
        prev = old.get(name)
        if not isinstance(prev, dict) or prev.get("type") != snap.get("type"):
            continue
        t = snap.get("type")
        if t == "counter":
            d = snap.get("value", 0) - prev.get("value", 0)
        elif t == "throughput":
            d = snap.get("total", 0) - prev.get("total", 0)
        elif t == "stage":
            d = {"count": snap.get("count", 0) - prev.get("count", 0),
                 "total_sec": (snap.get("total_sec", 0.0)
                               - prev.get("total_sec", 0.0))}
        elif t == "histogram":
            d = snap.get("count", 0) - prev.get("count", 0)
        else:
            continue
        if d not in (0, 0.0):
            out[name] = d
    return out


class FlightRecorder:
    """Bounded black box + incident dumper (see module doc)."""

    def __init__(self, snapshot_capacity: int = 32,
                 note_capacity: int = 256) -> None:
        self._lock = threading.Lock()
        self._snaps: deque = deque(maxlen=max(2, int(snapshot_capacity)))
        self._notes: deque = deque(maxlen=max(1, int(note_capacity)))
        self._dir: Optional[str] = get_env("DMLC_FLIGHT_DIR", None) or None
        self._min_interval = get_env("DMLC_FLIGHT_MIN_INTERVAL", 30.0)
        self._last_dump = -float("inf")
        self._dump_seq = 0
        self._installed = False
        self._prev_excepthook = None
        self._prev_thread_hook = None

    # -- arming ----------------------------------------------------------
    @property
    def armed(self) -> bool:
        return self._dir is not None

    def arm(self, directory: str) -> "FlightRecorder":
        """Enable disk dumps into ``directory`` (created on first dump)."""
        self._dir = directory
        return self

    def disarm(self) -> None:
        self._dir = None

    # -- feeding the rings ----------------------------------------------
    def note(self, kind: str, **attrs: Any) -> None:
        """Record a notable event (injected fault, SLO breach, stall,
        retrace alert) into the bounded note ring."""
        rec = {"kind": kind, "ts": time.time(), **attrs}
        with self._lock:
            self._notes.append(rec)

    def note_snapshot(self, registry: Optional[MetricsRegistry] = None
                      ) -> None:
        """Add a registry snapshot to the delta ring (SLO monitor ticks
        and telemetry pushes call this on their cadence)."""
        reg = registry if registry is not None else metrics
        snap = reg.snapshot()
        with self._lock:
            self._snaps.append((time.time(), snap))

    def notes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._notes)

    # -- bundling --------------------------------------------------------
    def bundle(self, reason: str,
               registry: Optional[MetricsRegistry] = None,
               **detail: Any) -> Dict[str, Any]:
        """The in-memory incident bundle (what ``/flight`` returns and
        what :meth:`dump` writes, minus the file layout)."""
        reg = registry if registry is not None else metrics
        now_snap = reg.snapshot()
        with self._lock:
            oldest = self._snaps[0] if self._snaps else None
            notes = list(self._notes)
        delta = None
        if oldest is not None:
            delta = {"since_ts": oldest[0],
                     "deltas": _counter_deltas(oldest[1], now_snap)}
        anomaly_mod = sys.modules.get("dmlc_core_tpu.telemetry.anomaly")
        faults_mod = sys.modules.get("dmlc_core_tpu.utils.faults")
        rank = get_env("DMLC_RANK", None)
        with _contrib_lock:
            contribs = dict(_contributors)
        sections: Dict[str, Any] = {}
        for name, fn in contribs.items():
            try:
                sections[name] = fn()
            except Exception as e:   # a contributor must not kill the dump
                sections[name] = f"<contributor failed: {e}>"
        # incident-time stacks: what every thread was doing when the
        # trigger fired (short bounded window; DMLC_FLIGHT_PROFILE_S=0
        # opts out)
        try:
            from . import profiling as _profiling
            sections["profile_collapsed"] = _profiling.incident_profile()
        except Exception as e:
            sections["profile_collapsed"] = f"<profiler failed: {e}>"
        # the before/after the snapshot can't give: the surrounding
        # timeline slice and where the slow requests actually spent
        # their time (both skipped when empty — a bundle from a process
        # with no sampler or no spans stays lean)
        try:
            from . import timeseries as _timeseries
            tl = _timeseries.history.snapshot_doc()
            if tl.get("series"):
                sections["timeline"] = tl
        except Exception as e:
            sections["timeline"] = f"<timeline failed: {e}>"
        try:
            from . import critical_path as _critical_path
            breakdown = _critical_path.incident_breakdown()
            if breakdown:
                sections["critical_path"] = breakdown
        except Exception as e:
            sections["critical_path"] = f"<critical path failed: {e}>"
        # the differential profile (r20): incident-window stacks vs the
        # last healthy /profile scrape — only when a baseline exists
        try:
            from . import profiling as _profiling_diff
            prof_text = sections.get("profile_collapsed")
            if isinstance(prof_text, str) and prof_text \
                    and not prof_text.startswith("<"):
                pdiff = _profiling_diff.incident_profile_diff(prof_text)
                if pdiff:
                    sections["profile_diff"] = pdiff
        except Exception as e:
            sections["profile_diff"] = f"<profile diff failed: {e}>"
        # the ranked root-cause verdict (r20): breach-scoped when a burn
        # rule just fired, default-window otherwise (DMLC_DIAGNOSE=0
        # opts out; skipped-when-None keeps unrelated bundles lean)
        try:
            from . import diagnose as _diagnose
            ddoc = _diagnose.incident_diagnosis()
            if ddoc is not None:
                sections["diagnosis"] = ddoc
        except Exception as e:
            sections["diagnosis"] = f"<diagnosis failed: {e}>"
        return {
            **sections,
            "schema": INCIDENT_SCHEMA,
            "reason": reason,
            "detail": detail,
            "ts": time.time(),
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "rank": int(rank) if rank and rank.lstrip("-").isdigit()
                    else None,
            "slo_spec": (anomaly_mod.active_slo_spec()
                         if anomaly_mod is not None else None),
            "fault_spec": (faults_mod.active_spec()
                           if faults_mod is not None else None),
            "metrics": now_snap,
            "metrics_delta": delta,
            "notes": notes,
            "span_count": len(_trace.recorder),
        }

    # -- dumping ---------------------------------------------------------
    def dump(self, reason: str, directory: Optional[str] = None,
             registry: Optional[MetricsRegistry] = None,
             force: bool = False, **detail: Any) -> Optional[str]:
        """Write an incident bundle; returns its directory, or None when
        not armed / rate-limited.  ``force`` bypasses the rate limit
        (explicit ``/flight`` hits and fatal paths use it — the last
        dump before death must never be suppressed)."""
        out_root = directory or self._dir
        if out_root is None:
            return None
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_dump < self._min_interval:
                return None
            self._last_dump = now
            self._dump_seq += 1
            seq = self._dump_seq
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        safe_reason = "".join(c if c.isalnum() or c in "-_" else "_"
                              for c in reason) or "incident"
        path = os.path.join(out_root,
                            f"incident-{stamp}-{seq:03d}-{safe_reason}")
        try:
            os.makedirs(path, exist_ok=True)
            doc = self.bundle(reason, registry=registry, **detail)
            tail = get_log_tail()
            doc["files"] = {"incident": "incident.json",
                            "trace": "trace.json",
                            "log_tail": "log_tail.txt"}
            prof = doc.get("profile_collapsed")
            if isinstance(prof, str) and prof:
                doc["files"]["profile"] = "profile.txt"
            tl = doc.get("timeline")
            if isinstance(tl, dict) and tl.get("series"):
                doc["files"]["timeline"] = "timeline.json"
            cpath = doc.get("critical_path")
            if isinstance(cpath, str) and cpath:
                doc["files"]["critical_path"] = "critical_path.txt"
            pdiff = doc.get("profile_diff")
            if isinstance(pdiff, str) and pdiff:
                doc["files"]["profile_diff"] = "profile_diff.txt"
            diag = doc.get("diagnosis")
            if isinstance(diag, dict):
                doc["files"]["diagnosis"] = "diagnosis.json"
                doc["files"]["diagnosis_text"] = "diagnosis.txt"
            # tmp + rename per file: a crash mid-dump (likely — this IS
            # the crash path) must not leave a half-written bundle that
            # post-mortem tooling then chokes on
            def _put(name: str, write) -> None:
                tmp = os.path.join(path, f".{name}.tmp")
                with open(tmp, "w", encoding="utf-8") as f:
                    write(f)
                os.replace(tmp, os.path.join(path, name))

            _put("incident.json",
                 lambda f: json.dump(doc, f, indent=2, sort_keys=True,
                                     default=str))
            _put("trace.json", lambda f: json.dump(to_chrome_trace(), f))
            _put("log_tail.txt",
                 lambda f: f.write("\n".join(tail) + ("\n" if tail else "")))
            if isinstance(prof, str) and prof:
                # collapsed stacks as their own file: flamegraph.pl and
                # speedscope read the format directly, no JSON unwrapping
                _put("profile.txt", lambda f: f.write(prof + "\n"))
            if isinstance(tl, dict) and tl.get("series"):
                _put("timeline.json",
                     lambda f: json.dump(tl, f, indent=2, sort_keys=True,
                                         default=str))
            if isinstance(cpath, str) and cpath:
                _put("critical_path.txt", lambda f: f.write(cpath))
            if isinstance(pdiff, str) and pdiff:
                _put("profile_diff.txt", lambda f: f.write(pdiff + "\n"))
            if isinstance(diag, dict):
                _put("diagnosis.json",
                     lambda f: json.dump(diag, f, indent=2,
                                         sort_keys=True, default=str))
                from . import diagnose as _diagnose
                _put("diagnosis.txt",
                     lambda f: f.write(_diagnose.render_text(diag)))
        except OSError as e:
            # the black box must never become the crash: report and move on
            log_warning("flight recorder dump to %s failed: %s", path, e)
            return None
        log_info("flight recorder: incident bundle at %s (reason=%s)",
                 path, reason)
        return path

    # -- fatal-path installation ----------------------------------------
    def install(self, signals: bool = True, excepthook: bool = True) -> None:
        """Chain onto the process's fatal paths: unhandled exceptions in
        the main thread and worker threads, plus the catchable fatal
        signals (SIGTERM/SIGABRT — SIGKILL/SIGSEGV are not interceptable
        from Python; crash-loop coverage for those comes from the ring
        dumps of the PREVIOUS trigger).  Previous hooks/handlers keep
        running after the dump."""
        if self._installed:
            return
        self._installed = True
        if excepthook:
            self._prev_excepthook = sys.excepthook

            def _hook(tp, val, tb):
                self.dump("unhandled_exception", force=True,
                          error=f"{tp.__name__}: {val}")
                (self._prev_excepthook or sys.__excepthook__)(tp, val, tb)

            sys.excepthook = _hook
            self._prev_thread_hook = threading.excepthook

            def _thread_hook(args):
                if args.exc_type is not SystemExit:
                    self.dump("unhandled_thread_exception", force=True,
                              error=f"{args.exc_type.__name__}: "
                                    f"{args.exc_value}",
                              thread=getattr(args.thread, "name", "?"))
                (self._prev_thread_hook
                 or threading.__excepthook__)(args)

            threading.excepthook = _thread_hook
        if signals:
            for signame in ("SIGTERM", "SIGABRT"):
                signum = getattr(signal, signame, None)
                if signum is None:
                    continue
                try:
                    prev = signal.getsignal(signum)

                    def _handler(num, frame, prev=prev, name=signame):
                        self.dump("fatal_signal", force=True, signal=name)
                        if callable(prev):
                            prev(num, frame)
                        else:
                            signal.signal(num, signal.SIG_DFL)
                            signal.raise_signal(num)

                    signal.signal(signum, _handler)
                except (ValueError, OSError):
                    pass    # not the main thread / exotic platform


#: process-global recorder (triggers from faults/anomaly/serving feed it)
flight_recorder = FlightRecorder()


def dump_incident(reason: str, registry: Optional[MetricsRegistry] = None,
                  **detail: Any) -> Optional[str]:
    """Module-level dump on the global recorder (rate-limited, no-op when
    unarmed) — the one-liner trigger sites call."""
    return flight_recorder.dump(reason, registry=registry, **detail)


def note(kind: str, **attrs: Any) -> None:
    """Record a notable event on the global recorder (the one-liner the
    anomaly detectors call via sys.modules)."""
    flight_recorder.note(kind, **attrs)


def note_fault(site: str) -> None:
    """Called by ``utils.faults`` (via sys.modules — no import edge) on
    every injected error: record it, and when armed leave a bundle so the
    chaos run's evidence trail matches a real incident's."""
    flight_recorder.note("fault_injected", site=site)
    metrics.counter("flight.fault_triggers").add(1)
    flight_recorder.dump("injected_fault", site=site)


def maybe_arm_from_env(install: bool = True) -> Optional[FlightRecorder]:
    """Arm the global recorder when ``DMLC_FLIGHT_DIR`` is set; also
    install the fatal-path hooks (``DMLC_FLIGHT_HOOKS=0`` opts out).
    Unset → None, exact no-op — the faults/SLO env convention."""
    directory = get_env("DMLC_FLIGHT_DIR", None) or None
    if directory is None:
        return None
    flight_recorder.arm(directory)
    if install and get_env("DMLC_FLIGHT_HOOKS", 1):
        flight_recorder.install()
    return flight_recorder
