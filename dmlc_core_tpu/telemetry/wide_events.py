"""Wide events: one canonical JSON line per unit of served work.

Spans answer *where did this request go*; metrics answer *how much*.
Neither survives an incident post-mortem on its own: the span ring is
lossy by design (and now tail-sampled), and histograms cannot say which
model or replica produced their tail.  The wide event is the canonical-
log-line answer — **one** bounded-cardinality record per serving
request and per data-service lease, carrying every dimension an
analyst would group by (model, replica, rows/nnz, queue wait, retries,
failovers, outcome, trace id, sampling verdict) — so post-hoc analytics
never depend on what the span ring happened to retain.

The vocabulary is closed: :data:`FIELDS` is the complete field set,
mirrored by the table in ``docs/observability.md`` and enforced both
ways by the ``wide-event-vocabulary`` dmlclint rule.  Unknown fields
are dropped and counted, never silently admitted — cardinality stays
bounded by construction.

Events land in a process-global ring (``DMLC_WIDE_EVENTS_CAP``, default
2048) served at ``/events?since=<seq>`` by every telemetry exporter,
optionally appended as JSON lines to ``DMLC_WIDE_EVENTS`` (the durable
audit file), and ride flight bundles via a lazily-registered
contributor.  Emission is :func:`wide_event` — the only sanctioned
spelling, which is what lets the lint rule find every call site.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.logging import log_warning
from ..utils.metrics import metrics
from ..utils.parameter import get_env
from . import trace as _trace

__all__ = ["FIELDS", "WideEventLog", "wide_log", "wide_event",
           "events_doc"]

WIDE_EVENTS_SCHEMA = "dmlc.telemetry.wide_events/1"

#: the closed field vocabulary — one row each in docs/observability.md
FIELDS = frozenset({
    "kind", "seq", "ts", "model", "replica", "conn", "req_id", "rows",
    "nnz", "batch_rows", "batch_nnz", "queue_ms", "dur_ms", "attempts",
    "retries", "hedges", "failovers", "outcome", "trace_id", "sampled",
    "debug", "worker", "part", "key", "lease_epoch", "epoch", "frames",
    "bytes", "endpoint", "qos",
})


class WideEventLog:
    """Bounded ring + optional append-only file of wide events.

    ``emit`` filters fields against :data:`FIELDS`, stamps ``seq``/
    ``ts`` and the ambient trace identity (``trace_id``/``debug``, plus
    the tail-sampling verdict as ``sampled`` when one is known), and
    appends.  The file path is append-only JSON lines — an audit log,
    not an artifact, so a write error disables the file (counted in
    ``telemetry.wide_events.file_errors``) instead of failing requests.
    """

    def __init__(self, capacity: Optional[int] = None,
                 path: Optional[str] = None) -> None:
        if capacity is None:
            capacity = int(get_env("DMLC_WIDE_EVENTS_CAP", 2048))
        if path is None:
            path = get_env("DMLC_WIDE_EVENTS", None)
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._dropped = 0
        self._path = path
        self._file = None
        self._file_dead = False
        self._registered = False

    # -- write path ------------------------------------------------------
    def emit(self, kind: str, **fields: Any) -> Dict[str, Any]:
        unknown = [k for k in fields if k not in FIELDS]
        if unknown:
            metrics.counter("telemetry.wide_events.unknown_fields").add(
                len(unknown))
            for k in unknown:
                fields.pop(k)
        ev: Dict[str, Any] = {"kind": str(kind),
                              "ts": round(time.time(), 6)}
        if "trace_id" not in fields:
            ctx = _trace.current()
            if ctx is not None:
                fields["trace_id"] = _trace.format_id(ctx.trace_id)
                fields.setdefault("debug",
                                  bool(ctx.trace_id & (1 << 63)))
        if "sampled" not in fields and fields.get("trace_id"):
            fields["sampled"] = self._verdict(fields["trace_id"])
        ev.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(ev)
            line = self._line_for_file(ev)
        metrics.counter("telemetry.wide_events.emitted").add(1)
        if line is not None:
            self._append(line)
        self._register_contributor()
        return ev

    @staticmethod
    def _verdict(trace_hex: str) -> Optional[bool]:
        import sys
        s = sys.modules.get("dmlc_core_tpu.telemetry.sampling")
        if s is None:
            return None
        return s.was_kept(trace_hex)

    def _line_for_file(self, ev: Dict[str, Any]) -> Optional[str]:
        if self._path is None or self._file_dead:
            return None
        return json.dumps(ev, sort_keys=True, separators=(",", ":"))

    def _append(self, line: str) -> None:
        try:
            with self._lock:
                if self._file is None:
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(line + "\n")
                self._file.flush()
        except OSError as e:
            with self._lock:
                self._file_dead = True
                self._file = None
            metrics.counter("telemetry.wide_events.file_errors").add(1)
            log_warning("wide events: disabling %r after write error: %s",
                        self._path, e)

    def _register_contributor(self) -> None:
        # lazy: only processes that actually emit wide events grow the
        # flight-bundle section, so bundles elsewhere are unchanged
        if self._registered:
            return
        self._registered = True
        try:
            from . import flight as _flight
            _flight.register_contributor(
                "wide_events", lambda: self.doc())
        except Exception as e:     # flight is optional at this layer
            log_warning("wide events: flight contributor not "
                        "registered: %s", e)

    # -- read path -------------------------------------------------------
    def snapshot(self, since: int = 0) -> List[Dict[str, Any]]:
        """Events with ``seq > since`` (the ``/events?since=`` cursor)."""
        with self._lock:
            if since <= 0:
                return list(self._buf)
            return [e for e in self._buf if e.get("seq", 0) > since]

    def doc(self, since: int = 0) -> Dict[str, Any]:
        """The ``/events`` response body / flight-bundle section.

        ``dropped`` is the cumulative ring-overflow count since the last
        :meth:`reset`.  ``missed`` is *this cursor's* loss: how many
        events with ``seq > since`` are gone from the ring (overflowed,
        or cleared by a reset — ``seq`` itself never restarts, so the
        arithmetic stays honest across both).  A resuming reader that
        sees ``missed == 0`` is guaranteed a gap-free, duplicate-free
        continuation of its previous read.
        """
        events = self.snapshot(since)
        with self._lock:
            last_seq, dropped = self._seq, self._dropped
            oldest = self._buf[0].get("seq", 0) if self._buf else None
        since = max(0, int(since))
        if oldest is not None:
            missed = max(0, oldest - 1 - since)
        else:
            missed = max(0, last_seq - since)
        return {"schema": WIDE_EVENTS_SCHEMA, "events": events,
                "last_seq": last_seq, "dropped": dropped,
                "missed": missed, "file": self._path}

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def reset(self, capacity: Optional[int] = None,
              path: Optional[str] = None) -> None:
        """Re-point the log (tests; long-lived processes after env
        changes).  Drops buffered events and closes any open file.
        ``seq`` is deliberately *not* restarted: cursors held by
        ``/events?since=`` readers must stay strictly monotonic, so a
        reader resuming across a reset reports the cleared events as
        ``missed`` instead of silently skipping (or re-reading) lines."""
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = None
            self._file_dead = False
            self._buf = deque(maxlen=max(1, int(
                capacity if capacity is not None
                else get_env("DMLC_WIDE_EVENTS_CAP", 2048))))
            self._dropped = 0
            self._path = path if path is not None \
                else get_env("DMLC_WIDE_EVENTS", None)


#: process-global log — what /events serves and flight bundles attach
wide_log = WideEventLog()


def wide_event(kind: str, **fields: Any) -> Dict[str, Any]:
    """Emit one wide event into the global log.  This is the *only*
    sanctioned call spelling — the ``wide-event-vocabulary`` lint rule
    keys on the function name to check field vocabulary at every site."""
    return wide_log.emit(kind, **fields)


def events_doc(since: int = 0) -> Dict[str, Any]:
    """The global log's ``/events`` document (exposition default fn)."""
    return wide_log.doc(since)
