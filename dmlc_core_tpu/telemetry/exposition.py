"""Prometheus text exposition + stdlib HTTP exporter.

:func:`render_prometheus` turns any ``MetricsRegistry.snapshot()`` into
Prometheus text format 0.0.4 — no client library, just the format:

* counter      → ``dmlc_<name>_total``
* gauge        → ``dmlc_<name>``
* histogram    → summary-style ``{quantile="0.5|0.95|0.99"}`` series plus
  ``_sum`` / ``_count`` (reservoir quantiles are pre-computed, which is a
  summary, not a Prometheus histogram's cumulative buckets)
* throughput   → ``_total`` counter + ``_rate`` / ``_windowed_rate`` gauges
* stage        → ``_seconds_total`` counter + ``_count`` + ``_mean_seconds``

:func:`render_series` renders several labeled snapshots (e.g. one per
rank plus a merged fleet view) into one page with each ``# TYPE`` header
emitted once per family, which is what the tracker's ``/metrics`` serves.

:func:`render_openmetrics` is the OpenMetrics 1.0 sibling
(``/metrics?format=openmetrics``): histograms become native cumulative
buckets (synthesised at the reservoir's p50/p95/p99 edges) so retained
exemplars — ``(value, trace_id, ts)`` triples captured by
``Histogram.observe`` — can ride the ``_bucket`` lines in standard
``# {trace_id="..."}`` syntax.  When a tail sampler is installed only
exemplars whose traces were *kept* are rendered, so every exemplar on
the page is followable into ``/spans``.

:class:`TelemetryServer` is a daemon-thread ``ThreadingHTTPServer``
mounting ``/metrics``, ``/healthz``, and ``/spans``.  The serving server
mounts one when ``metrics_port`` / ``DMLC_METRICS_PORT`` is set, the
tracker mounts one for the fleet view, and
:func:`maybe_start_from_env` lets any process self-serve its registry.
"""

from __future__ import annotations

import json
import os
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import log_info, log_warning
from ..utils.parameter import get_env
from . import trace as _trace

__all__ = ["render_prometheus", "render_series", "render_openmetrics",
           "render_fleet_board", "TelemetryServer", "maybe_start_from_env"]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")

#: health states a health_fn may return, with their HTTP mapping
_HEALTH_HTTP = {"ok": 200, "degraded": 200, "overloaded": 503}

#: route table: URL path → TelemetryServer handler method name.  Every
#: endpoint is declared through :func:`_endpoint` so the set is one
#: greppable table — the dmlclint ``endpoint-vocabulary`` rule checks
#: these literals against the docs/observability.md endpoint table.
_ROUTES: Dict[str, str] = {}


def _endpoint(path: str):
    """Register a ``TelemetryServer`` method as the handler for ``path``
    (handlers return ``(status, content_type, body)``)."""

    def deco(fn):
        _ROUTES[path] = fn.__name__
        return fn

    return deco


#: metric-name → one-line help text, lazily loaded from the committed
#: ``docs/inventory.json`` catalog (``# HELP`` sourcing); missing or
#: unreadable inventory degrades to no HELP lines, never an error
_HELP_CACHE: Optional[Dict[str, str]] = None


def _help_catalog() -> Dict[str, str]:
    global _HELP_CACHE
    if _HELP_CACHE is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "..", "docs", "inventory.json")
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            helps = doc.get("help", {})
            _HELP_CACHE = {k: str(v) for k, v in helps.items()
                           if isinstance(v, str)}
        except (OSError, ValueError):
            _HELP_CACHE = {}
    return _HELP_CACHE


def _escape_help(text: str) -> str:
    """Text-format 0.0.4 HELP escaping: backslash and newline only."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _sanitize(name: str) -> str:
    """``serving.client.retries`` → ``serving_client_retries``."""
    name = _NAME_BAD.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _escape_label_value(v: Any) -> str:
    """Text-format 0.0.4 label-value escaping: backslash first (so the
    escapes it introduces aren't re-escaped), then newline and quote."""
    return (str(v).replace("\\", "\\\\")
                  .replace("\n", "\\n")
                  .replace('"', '\\"'))


def _fmt_labels(labels: Optional[Dict[str, str]],
                extra: Optional[Dict[str, str]] = None) -> str:
    merged: Dict[str, str] = dict(labels or {})
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _fmt_val(v: Any) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _family_samples(name: str, snap: Dict[str, Any],
                    labels: Optional[Dict[str, str]], prefix: str
                    ) -> List[Tuple[str, str, List[str]]]:
    """One snapshot entry → list of (family_name, prom_type, sample_lines)."""
    base = f"{prefix}_{_sanitize(name)}" if prefix else _sanitize(name)
    t = snap.get("type")
    lab = lambda extra=None: _fmt_labels(labels, extra)  # noqa: E731
    if t == "counter":
        return [(f"{base}_total", "counter",
                 [f"{base}_total{lab()} {_fmt_val(snap.get('value', 0))}"])]
    if t == "gauge":
        return [(base, "gauge",
                 [f"{base}{lab()} {_fmt_val(snap.get('value', 0.0))}"])]
    if t == "histogram":
        count = int(snap.get("count", 0))
        mean = float(snap.get("mean", 0.0))
        lines = [
            f"{base}{lab({'quantile': q})} {_fmt_val(snap.get(p, 0.0))}"
            for q, p in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))
        ]
        lines.append(f"{base}_sum{lab()} {_fmt_val(mean * count)}")
        lines.append(f"{base}_count{lab()} {count}")
        return [(base, "summary", lines)]
    if t == "throughput":
        return [
            (f"{base}_total", "counter",
             [f"{base}_total{lab()} {_fmt_val(snap.get('total', 0))}"]),
            (f"{base}_rate", "gauge",
             [f"{base}_rate{lab()} {_fmt_val(snap.get('rate', 0.0))}"]),
            (f"{base}_windowed_rate", "gauge",
             [f"{base}_windowed_rate{lab()} "
              f"{_fmt_val(snap.get('windowed_rate', 0.0))}"]),
        ]
    if t == "stage":
        return [
            (f"{base}_seconds_total", "counter",
             [f"{base}_seconds_total{lab()} "
              f"{_fmt_val(snap.get('total_sec', 0.0))}"]),
            (f"{base}_count", "counter",
             [f"{base}_count{lab()} {_fmt_val(snap.get('count', 0))}"]),
            (f"{base}_mean_seconds", "gauge",
             [f"{base}_mean_seconds{lab()} "
              f"{_fmt_val(snap.get('mean_sec', 0.0))}"]),
        ]
    return []   # unknown type: skip rather than emit malformed text


def render_series(series: Sequence[Tuple[Optional[Dict[str, str]],
                                         Dict[str, Dict[str, Any]]]],
                  prefix: str = "dmlc",
                  help_map: Optional[Dict[str, str]] = None) -> str:
    """Render labeled snapshots into one exposition page.

    ``series`` is ``[(labels_or_None, snapshot), ...]``; samples of the
    same family from different label sets share a single ``# TYPE``
    header (duplicated headers are invalid exposition format).  Each
    family whose source metric has a row in the ``docs/inventory.json``
    help catalog gets a ``# HELP`` line (``help_map`` overrides the
    catalog; pass ``{}`` to disable).
    """
    if help_map is None:
        help_map = _help_catalog()
    families: Dict[str, Tuple[str, List[str]]] = {}
    order: List[str] = []
    sources: Dict[str, str] = {}      # family → source metric name
    for labels, snapshot in series:
        for name in sorted(snapshot):
            for fam, ptype, lines in _family_samples(
                    name, snapshot[name], labels, prefix):
                if fam not in families:
                    families[fam] = (ptype, [])
                    order.append(fam)
                    sources[fam] = name
                families[fam][1].extend(lines)
    out: List[str] = []
    for fam in order:
        ptype, lines = families[fam]
        help_text = help_map.get(sources.get(fam, ""))
        if help_text:
            out.append(f"# HELP {fam} {_escape_help(help_text)}")
        out.append(f"# TYPE {fam} {ptype}")
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


def render_prometheus(snapshot: Dict[str, Dict[str, Any]],
                      labels: Optional[Dict[str, str]] = None,
                      prefix: str = "dmlc",
                      help_map: Optional[Dict[str, str]] = None) -> str:
    """Prometheus text format 0.0.4 for one registry snapshot."""
    return render_series([(labels, snapshot)], prefix=prefix,
                         help_map=help_map)


def _exemplar_kept(trace_hex: Optional[str]) -> bool:
    """Should this exemplar's trace be shown?  With no tail sampler
    installed everything is recorded, so every trace is followable;
    with one, only a kept verdict (not drop, not unknown) qualifies."""
    if not trace_hex:
        return False
    from . import sampling as _sampling
    s = _sampling.get_sampler()
    if s is None:
        return True
    was = getattr(s, "was_kept", None)
    if was is None:
        return True
    return was(trace_hex) is True


def _registry_exemplars(metric: Optional[str] = None
                        ) -> Dict[str, List[Dict[str, Any]]]:
    """Kept-trace exemplars held by live registry histograms, keyed by
    metric name (optionally restricted to one metric)."""
    from ..utils.metrics import metrics as _registry
    out: Dict[str, List[Dict[str, Any]]] = {}
    for name, snap in _registry.snapshot().items():
        if metric is not None and name != metric:
            continue
        exs = [e for e in (snap.get("exemplars") or [])
               if _exemplar_kept(e.get("trace_id"))]
        if exs:
            out[name] = exs
    return out


def _openmetrics_histogram(base: str, snap: Dict[str, Any],
                           lab: Callable[..., str]) -> List[str]:
    """Native-histogram lines with exemplars.  The reservoir stores
    quantiles, not buckets, so cumulative buckets are synthesised at the
    p50/p95/p99 edges — coarse, but enough structure for exemplars to
    attach where the spec allows them (``_bucket`` samples only)."""
    count = int(snap.get("count", 0))
    mean = float(snap.get("mean", 0.0))
    exs = [e for e in (snap.get("exemplars") or [])
           if _exemplar_kept(e.get("trace_id"))]
    exs.sort(key=lambda e: float(e.get("value", 0.0)))
    lines: List[str] = []
    idx = 0
    for hi, frac in ((float(snap.get("p50", 0.0)), 0.50),
                     (float(snap.get("p95", 0.0)), 0.95),
                     (float(snap.get("p99", 0.0)), 0.99),
                     (None, 1.0)):
        c = count if hi is None else int(round(count * frac))
        le = "+Inf" if hi is None else _fmt_val(hi)
        line = f"{base}_bucket{lab({'le': le})} {c}"
        if idx < len(exs) and (hi is None or
                               float(exs[idx].get("value", 0.0)) <= hi):
            e = exs[idx]
            idx += 1
            tid = _escape_label_value(e.get("trace_id", ""))
            line += (f' # {{trace_id="{tid}"}}'
                     f' {_fmt_val(e.get("value", 0.0))}'
                     f' {_fmt_val(e.get("ts", 0.0))}')
        lines.append(line)
    lines.append(f"{base}_sum{lab()} {_fmt_val(mean * count)}")
    lines.append(f"{base}_count{lab()} {count}")
    return lines


def render_openmetrics(snapshot: Dict[str, Dict[str, Any]],
                       labels: Optional[Dict[str, str]] = None,
                       prefix: str = "dmlc",
                       help_map: Optional[Dict[str, str]] = None) -> str:
    """OpenMetrics 1.0 text for one registry snapshot, ``# EOF``
    terminated.  Counters drop the ``_total`` suffix from the *family*
    name (the sample keeps it, per spec); histograms render as native
    cumulative buckets carrying kept-trace exemplars."""
    if help_map is None:
        help_map = _help_catalog()
    lab = lambda extra=None: _fmt_labels(labels, extra)  # noqa: E731
    out: List[str] = []
    for name in sorted(snapshot):
        snap = snapshot[name]
        help_text = help_map.get(name)
        if snap.get("type") == "histogram":
            base = (f"{prefix}_{_sanitize(name)}" if prefix
                    else _sanitize(name))
            if help_text:
                out.append(f"# HELP {base} {_escape_help(help_text)}")
            out.append(f"# TYPE {base} histogram")
            out.extend(_openmetrics_histogram(base, snap, lab))
            continue
        for fam, ptype, lines in _family_samples(name, snap, labels,
                                                 prefix):
            om_fam = (fam[:-len("_total")]
                      if ptype == "counter" and fam.endswith("_total")
                      else fam)
            if help_text:
                out.append(f"# HELP {om_fam} {_escape_help(help_text)}")
            out.append(f"# TYPE {om_fam} {ptype}")
            out.extend(lines)
    out.append("# EOF")
    return "\n".join(out) + "\n"


def _text_table(headers: List[str], rows: List[List[str]]) -> List[str]:
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*headers), fmt.format(*("-" * w for w in widths))]
    out.extend(fmt.format(*r) for r in rows)
    return out


def render_fleet_board(doc: Dict[str, Any], html: bool = False) -> str:
    """Zero-dependency status board over a dispatcher ``/fleet`` doc.

    Plain aligned text (also legible in a terminal via ``curl``); with
    ``html=True`` the same text is wrapped in a minimal self-refreshing
    page — no JS, no CSS framework, nothing to vendor.
    """
    replicas = doc.get("replicas", {}) or {}
    lines: List[str] = ["serving fleet" if replicas
                        else "data-service fleet"]
    workers = doc.get("workers", {}) or {}
    if workers or not replicas:
        rows = []
        for jobid in sorted(workers):
            w = workers[jobid]
            rows.append([
                jobid,
                str(w.get("addr", "?")),
                "DEAD" if not w.get("alive", True) else
                ("straggler" if w.get("straggler") else "up"),
                f"{w.get('heartbeat_age_s', 0.0):.1f}s",
                f"{w.get('mb_s', 0.0):.1f}",
                str(w.get("live_leases", 0)),
                str(w.get("shards", 0)),
            ])
        lines.append("")
        lines.extend(_text_table(
            ["worker", "addr", "state", "hb_age", "MB/s", "leases",
             "shards"], rows))
    if replicas:
        # serving-fleet console (registry or router /fleet docs): one
        # row per replica, health word + the balancer's load facts
        rows = []
        for jobid in sorted(replicas):
            r = replicas[jobid]
            hb = r.get("heartbeat_age_s")
            rows.append([
                jobid,
                str(r.get("model_id", "?")),
                str(r.get("addr", "?")),
                "DEAD" if not r.get("alive", True) else
                ("straggler" if r.get("straggler")
                 else str(r.get("health", "?"))),
                f"{hb:.1f}s" if isinstance(hb, (int, float)) else "-",
                f"{r.get('queue_fraction', 0.0):.2f}",
                str(r.get("inflight", 0)),
                str(r.get("step", "-")),
            ])
        lines.append("")
        lines.extend(_text_table(
            ["replica", "model", "addr", "state", "hb_age", "q_frac",
             "inflight", "step"], rows))
        models = doc.get("models", {}) or {}
        if models:
            lines.append("")
            lines.extend(_text_table(
                ["model", "stable_ckpt", "step", "replicas"],
                [[m, str(d.get("ckpt_dir", "-")), str(d.get("step", "-")),
                  str(len(d.get("replicas", [])))]
                 for m, d in sorted(models.items())]))
    consumers = doc.get("consumers", {}) or {}
    if consumers:
        lines.append("")
        lines.extend(_text_table(
            ["consumer", "backlog", "age"],
            [[k, str(c.get("backlog", 0)), f"{c.get('age_s', 0.0):.1f}s"]
             for k, c in sorted(consumers.items())]))
    datasets = doc.get("datasets", {}) or {}
    if datasets:
        lines.append("")
        lines.extend(_text_table(
            ["dataset", "epoch", "pending", "granted", "completed"],
            [[k, str(d.get("epoch", 0)), str(d.get("pending", 0)),
              str(d.get("granted", 0)), str(d.get("completed", 0))]
             for k, d in sorted(datasets.items())]))
    text = "\n".join(lines) + "\n"
    if not html:
        return text
    import html as _html
    return ("<!doctype html><html><head>"
            "<meta http-equiv=\"refresh\" content=\"2\">"
            "<title>dmlc fleet</title></head><body><pre>"
            + _html.escape(text) + "</pre></body></html>\n")


class TelemetryServer:
    """Daemon-thread HTTP exporter: ``/metrics`` (Prometheus text;
    ``?format=openmetrics`` adds exemplar-bearing OpenMetrics),
    ``/healthz`` (JSON status, 503 when overloaded), ``/spans`` (recent
    span records as JSON, with the ring's eviction count), ``/events``
    (wide-event audit ring, ``?since=<seq>`` cursor),
    ``/flight`` (on-demand incident bundle),
    ``/stragglers`` (tracker only — cross-rank straggler board JSON),
    ``/profile?seconds=N`` (collapsed-stack sampling profile of this
    process; plain scrapes double as the baseline recorder and
    ``?diff=1`` serves the differential profile against that baseline),
    ``/timeline?metric=&since=&format=json|text`` (the
    time-machine history store — process-local by default, the merged
    fleet store on the tracker/dispatcher), ``/analyze?top=N``
    (critical-path breakdown of the slowest traces in the span ring),
    ``/diagnose?since=&until=&top=&format=json|text`` (the r20 automated
    incident diagnosis: four analyzers merged into one ranked suspect
    report — fleet-merged on hosts that inject their stores),
    and — when the hosting process injects them — ``/leases``
    (dispatcher lease-lifecycle ledger), ``/fleet`` (dispatcher worker
    or serving replica console; ``?format=text|html`` renders the
    status board instead of JSON) and ``/rollouts`` (serving-fleet
    canary rollout ledger).

    All content callbacks are injectable so the same class serves a
    process-local registry (serving server, standalone exporter) or the
    tracker's merged fleet view.  ``port=0`` binds an ephemeral port —
    read it back from :attr:`port` (tests and same-host discovery).
    """

    def __init__(self, port: int = 0, host: str = "0.0.0.0", *,
                 metrics_fn: Optional[Callable[[], str]] = None,
                 health_fn: Optional[Callable[[], str]] = None,
                 spans_fn: Optional[Callable[[], List[Dict[str, Any]]]] = None,
                 flight_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 stragglers_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 leases_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 fleet_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 profile_fn: Optional[Callable[[float], str]] = None,
                 rollouts_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 timeline_fn: Optional[Callable[[Optional[str],
                                                 Optional[float]],
                                                Dict[str, Any]]] = None,
                 analyze_fn: Optional[Callable[[int],
                                               Dict[str, Any]]] = None,
                 diagnose_fn: Optional[Callable[[Optional[float],
                                                 Optional[float],
                                                 Optional[int]],
                                                Dict[str, Any]]] = None,
                 ) -> None:
        if metrics_fn is None:
            from ..utils.metrics import metrics as _registry
            metrics_fn = lambda: render_prometheus(_registry.snapshot())  # noqa: E731
        if health_fn is None:
            health_fn = self._default_health
        if spans_fn is None:
            spans_fn = _trace.recorder.snapshot
        if flight_fn is None:
            flight_fn = self._default_flight
        if profile_fn is None:
            profile_fn = self._default_profile
        if analyze_fn is None:
            analyze_fn = self._default_analyze
        if diagnose_fn is None:
            diagnose_fn = self._default_diagnose
        self._metrics_fn = metrics_fn
        self._health_fn = health_fn
        self._spans_fn = spans_fn
        self._flight_fn = flight_fn
        self._stragglers_fn = stragglers_fn
        self._leases_fn = leases_fn
        self._fleet_fn = fleet_fn
        self._profile_fn = profile_fn
        self._rollouts_fn = rollouts_fn
        # None → the process-global history store, resolved (and its
        # sampler started, DMLC_TIMELINE permitting) at start()
        self._timeline_fn = timeline_fn
        self._analyze_fn = analyze_fn
        self._diagnose_fn = diagnose_fn
        self._requested = (host, int(port))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_flight() -> Dict[str, Any]:
        """``GET /flight``: build (and, when armed, dump to disk) an
        incident bundle from the process-global flight recorder."""
        from . import flight as _flight
        path = _flight.flight_recorder.dump("endpoint", force=True)
        doc = _flight.flight_recorder.bundle("endpoint")
        if path is not None:
            doc["dumped_to"] = path
        return doc

    @staticmethod
    def _default_profile(seconds: float) -> str:
        """``GET /profile?seconds=N``: one bounded sampling window of
        every thread in this process, collapsed-stack text."""
        from . import profiling as _profiling
        return _profiling.profile_for(seconds)

    @staticmethod
    def _default_health() -> str:
        """Standalone exporters report the serving health gauge when the
        process runs a server (0 ok / 1 degraded / 2 overloaded); a
        process with no server still degrades on live SLO breaches
        (``slo.active_breaches`` > 0 — the burn-rate engine's handle on
        ``/healthz``), else ok."""
        from ..utils.metrics import metrics as _registry
        v = _registry.gauge("serving.server.health").value
        status = {0: "ok", 1: "degraded", 2: "overloaded"}.get(int(v), "ok")
        if status == "ok" and \
                _registry.gauge("slo.active_breaches").value > 0:
            return "degraded"
        return status

    @staticmethod
    def _default_analyze(top: int) -> Dict[str, Any]:
        """``GET /analyze?top=N``: critical-path breakdown of the N
        slowest traces in this process's span ring."""
        from . import critical_path as _critical_path
        return _critical_path.analyze(top=top)

    @staticmethod
    def _default_diagnose(since_s: Optional[float],
                          until_s: Optional[float],
                          top: Optional[int]) -> Dict[str, Any]:
        """``GET /diagnose``: automated incident diagnosis over this
        process's wide-event ring, history store and span ring.  Hosts
        with merged fleet stores (tracker/dispatcher/registry) inject a
        fleet-scoped engine instead."""
        from . import diagnose as _diagnose
        return _diagnose.default_engine().endpoint_doc(
            since_s=since_s, until_s=until_s, top=top)

    @property
    def port(self) -> int:
        """The bound port (resolves ``port=0`` ephemeral binds)."""
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._requested[1]

    # -- endpoint handlers -------------------------------------------------
    # Each returns (status, content_type, body-str); registration via
    # @_endpoint keeps the route vocabulary a single greppable table.

    @staticmethod
    def _json(doc: Any, code: int = 200) -> Tuple[int, str, str]:
        return code, "application/json", json.dumps(doc, default=str)

    @_endpoint("/metrics")
    def _ep_metrics(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        if query.get("format") == "openmetrics":
            # exemplar-bearing rendering needs the raw snapshot, so this
            # branch serves the process-local registry (a tracker's
            # injected merged view stays on the default format)
            from ..utils.metrics import metrics as _registry
            return (200, "application/openmetrics-text; version=1.0.0; "
                         "charset=utf-8",
                    render_openmetrics(_registry.snapshot()))
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                self._metrics_fn())

    @_endpoint("/healthz")
    def _ep_healthz(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        # a health_fn may return the bare status word or a full JSON doc
        # with a "status" key (serving replicas add queue_fraction/
        # inflight so load balancers weight off this one endpoint)
        status = self._health_fn()
        doc = status if isinstance(status, dict) else {"status": status}
        return self._json(doc, _HEALTH_HTTP.get(str(doc.get("status")), 200))

    @_endpoint("/spans")
    def _ep_spans(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        # the ring is lossy: stamp how many records it has evicted so a
        # consumer can tell a quiet process from a saturated window
        return self._json({"spans": self._spans_fn(),
                           "dropped": _trace.recorder.dropped})

    @_endpoint("/events")
    def _ep_events(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        try:
            since = int(query.get("since", "0") or 0)
        except ValueError:
            since = 0
        from . import wide_events as _wide
        return self._json(_wide.events_doc(since))

    @_endpoint("/flight")
    def _ep_flight(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        return self._json(self._flight_fn())

    @_endpoint("/stragglers")
    def _ep_stragglers(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        if self._stragglers_fn is None:
            # worker exporters have no cross-rank view — only the
            # tracker mounts a straggler board
            return (404, "text/plain",
                    "no straggler board here (tracker-only endpoint)\n")
        return self._json(self._stragglers_fn())

    @_endpoint("/leases")
    def _ep_leases(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        if self._leases_fn is None:
            # only the data-service dispatcher owns a lease table
            return (404, "text/plain",
                    "no lease ledger here (dispatcher-only endpoint)\n")
        return self._json(self._leases_fn())

    @_endpoint("/fleet")
    def _ep_fleet(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        if self._fleet_fn is None:
            return (404, "text/plain",
                    "no fleet console here (dispatcher-only endpoint)\n")
        doc = self._fleet_fn()
        fmt = query.get("format", "json")
        if fmt == "html":
            return (200, "text/html; charset=utf-8",
                    render_fleet_board(doc, html=True))
        if fmt == "text":
            return 200, "text/plain; charset=utf-8", render_fleet_board(doc)
        return self._json(doc)

    @_endpoint("/rollouts")
    def _ep_rollouts(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        if self._rollouts_fn is None:
            # only a replica registry (or a router proxying one) owns a
            # rollout ledger
            return (404, "text/plain",
                    "no rollout ledger here (registry/router endpoint)\n")
        return self._json(self._rollouts_fn())

    @_endpoint("/profile")
    def _ep_profile(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        try:
            seconds = float(query.get("seconds", "1"))
        except ValueError:
            seconds = 1.0
        text = self._profile_fn(seconds)
        from . import profiling as _profiling
        if query.get("diff") in ("1", "true", "yes"):
            # fresh window diffed against the last plain scrape — the
            # plain scrape IS the baseline recorder, so any periodic
            # profile collection arms this for free
            got = _profiling.baseline()
            if got is None:
                return (404, "text/plain; charset=utf-8",
                        "no baseline profile recorded yet — scrape "
                        "/profile (without diff=1) during a healthy "
                        "window first\n")
            return (200, "text/plain; charset=utf-8",
                    _profiling.incident_profile_diff(text))
        _profiling.record_baseline(text)
        return 200, "text/plain; charset=utf-8", text

    @_endpoint("/timeline")
    def _ep_timeline(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        from . import timeseries as _timeseries
        fn = self._timeline_fn or _timeseries.history.timeline
        metric = query.get("metric") or None
        since: Optional[float] = None
        raw_since = query.get("since")
        if raw_since:
            from .slo import parse_duration
            since = parse_duration(raw_since)   # "300", "5m", "90s" all ok
        doc = fn(metric, since)
        if query.get("format") == "text":
            return (200, "text/plain; charset=utf-8",
                    _timeseries.render_timeline_text(doc))
        exs = _registry_exemplars(metric)
        if exs:
            # exemplar trace ids bridge the aggregate view to /spans:
            # "the p99 spiked" → "this trace is the p99"
            doc = dict(doc)
            doc["exemplars"] = exs
        return self._json(doc)

    @_endpoint("/analyze")
    def _ep_analyze(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        try:
            top = int(query.get("top", "5"))
        except ValueError:
            top = 5
        doc = self._analyze_fn(top)
        if query.get("format") == "text":
            from . import critical_path as _critical_path
            return (200, "text/plain; charset=utf-8",
                    _critical_path.render_text(doc))
        exs = _registry_exemplars()
        if exs:
            doc = dict(doc)
            doc["exemplars"] = exs
        return self._json(doc)

    @_endpoint("/diagnose")
    def _ep_diagnose(self, query: Dict[str, str]) -> Tuple[int, str, str]:
        from .slo import parse_duration
        since_s = until_s = None
        raw = query.get("since")
        if raw:
            since_s = parse_duration(raw)     # "60", "5m", "90s" all ok
        raw = query.get("until")
        if raw:
            until_s = parse_duration(raw)
        top: Optional[int] = None
        try:
            top = int(query["top"]) if query.get("top") else None
        except ValueError:
            top = None
        doc = self._diagnose_fn(since_s, until_s, top)
        if query.get("format") == "text":
            from . import diagnose as _diagnose
            return (200, "text/plain; charset=utf-8",
                    _diagnose.render_text(doc))
        return self._json(doc)

    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        # default /timeline serves the process-global history store;
        # mounting an exporter is the "observability on" gesture, so it
        # also starts the sampler (DMLC_TIMELINE=0 opts out).  Hosts
        # that inject a fleet store (tracker/dispatcher) own its
        # lifecycle themselves.
        if self._timeline_fn is None:
            from . import timeseries as _timeseries
            _timeseries.maybe_start_sampler()
            self._timeline_fn = _timeseries.history.timeline
        # same gesture arms tail sampling (exact no-op unless
        # DMLC_TRACE_SAMPLE is set) so every tier that mounts an
        # exporter shares one coordination-free sampling config
        from . import sampling as _sampling
        _sampling.maybe_install_from_env()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # route into our logger
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_GET(self):   # noqa: N802 (http.server API)
                path, _, rawq = self.path.partition("?")
                query = {k: vs[-1] for k, vs
                         in urllib.parse.parse_qs(rawq).items()}
                handler = _ROUTES.get(path)
                try:
                    if handler is None:
                        code, ctype, body = 404, "text/plain", "not found\n"
                    else:
                        code, ctype, body = getattr(outer, handler)(query)
                except Exception as e:   # scrape must never kill the server
                    code, ctype, body = (500, "text/plain",
                                         f"exporter error: {e}\n")
                self._send(code, ctype, body.encode("utf-8")
                           if isinstance(body, str) else body)

        self._httpd = ThreadingHTTPServer(self._requested, Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dmlc-telemetry",
            daemon=True)
        self._thread.start()
        log_info("telemetry exporter listening on %s:%d (%s)",
                 self._requested[0], self.port,
                 " ".join(sorted(_ROUTES)))
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def maybe_start_from_env() -> Optional[TelemetryServer]:
    """Start a process-local exporter when ``DMLC_METRICS_PORT`` is set
    (0 = ephemeral).  Returns the running server or None.  Startup
    failures (port in use) are logged, not raised — telemetry must not
    take the workload down.

    Also activates the env-driven observability companions — the flight
    recorder (``DMLC_FLIGHT_DIR``) and the SLO monitor
    (``DMLC_SLO_SPEC``) — each an exact no-op when its env is unset, so
    one call is the whole "observability on" switch for any process.
    """
    from . import anomaly as _anomaly
    from . import flight as _flight
    from . import sampling as _sampling
    _flight.maybe_arm_from_env()
    _anomaly.maybe_monitor_from_env()
    _sampling.maybe_install_from_env()
    port = get_env("DMLC_METRICS_PORT", -1)
    if port < 0:
        return None
    try:
        return TelemetryServer(port=port).start()
    except OSError as e:
        log_warning("telemetry exporter failed to bind port %d: %s", port, e)
        return None
