"""Tracker-side merging of rank-tagged registry states.

Workers push ``MetricsRegistry.state()`` dicts (histograms carry their
reservoir samples) to the tracker over the tracker protocol; this module
folds a ``{rank: state}`` map into one fleet snapshot and renders the
combined ``/metrics`` page: merged series first (unlabeled — the scrape
target for dashboards), then every contributing rank re-rendered with a
``rank="N"`` label for drill-down.

Merge semantics live with the metric classes (``Counter.merge``,
``Histogram.merge`` over serialized reservoirs, ...); this module only
groups by name/type and skips conflicting types rather than guessing.

A restarted worker re-registers from zero, so its next push carries
counters BELOW what the fleet already banked — naive merging would drive
merged totals backwards and turn every rate derived from them negative.
:class:`ResetGuard` sits at the ingestion point (tracker telemetry
handler, dispatcher heartbeat): it keeps a per-``(rank, metric)``
baseline, detects any monotonic field going backwards, re-baselines so
the merged view stays monotonic, and counts each event in
``telemetry.counter_resets``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils.metrics import (Counter, Gauge, Histogram, StageTimer,
                             ThroughputMeter, metrics)
from .exposition import render_series

__all__ = ["merge_states", "state_to_snapshot", "render_fleet",
           "ResetGuard"]

_MERGERS = {
    "counter": Counter.merge,
    "gauge": Gauge.merge,
    "histogram": Histogram.merge,
    "throughput": ThroughputMeter.merge,
    "stage": StageTimer.merge,
}


def merge_states(per_rank: Dict[str, Dict[str, Dict[str, Any]]]
                 ) -> Dict[str, Dict[str, Any]]:
    """``{rank: {metric_name: state}}`` → merged snapshot-form dict.

    A metric name reported with different types by different ranks (a
    version skew symptom) is dropped from the merged view — the per-rank
    sections still show both sides of the skew.
    """
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for state in per_rank.values():
        for name, s in (state or {}).items():
            if isinstance(s, dict):
                by_name.setdefault(name, []).append(s)
    merged: Dict[str, Dict[str, Any]] = {}
    for name, states in sorted(by_name.items()):
        types = {s.get("type") for s in states}
        if len(types) != 1:
            continue
        merger = _MERGERS.get(next(iter(types)))
        if merger is not None:
            merged[name] = merger(states)
    return merged


#: per-type fields that must never go backwards for one live process
_MONOTONIC = {
    "counter": ("value",),
    "throughput": ("total",),
    "stage": ("count", "total_sec"),
    "histogram": ("count",),
}


class ResetGuard:
    """Counter-reset detection at the fleet ingestion point.

    ``fold(rank, state)`` returns an adjusted copy of one rank's pushed
    state: every monotonic field is re-based so that a restart (the
    field goes BACKWARDS) banks the pre-reset value into the baseline
    instead of subtracting it from the fleet.  Each reset event bumps
    ``telemetry.counter_resets`` once per metric, on the host registry.
    """

    def __init__(self, registry: Optional[Any] = None) -> None:
        self._registry = registry if registry is not None else metrics
        self._lock = threading.Lock()
        # (rank, metric) -> {field: (banked_base, last_raw)}
        self._bases: Dict[Tuple[str, str],
                          Dict[str, Tuple[float, float]]] = {}

    def fold(self, rank: Any, state: Dict[str, Dict[str, Any]]
             ) -> Dict[str, Dict[str, Any]]:
        resets = 0
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for name, s in (state or {}).items():
                if not isinstance(s, dict):
                    continue
                fields = _MONOTONIC.get(s.get("type"))
                if not fields:
                    out[name] = s
                    continue
                bases = self._bases.setdefault((str(rank), name), {})
                adj = dict(s)
                was_reset = False
                for f in fields:
                    try:
                        raw = float(s.get(f, 0.0))
                    except (TypeError, ValueError):
                        continue
                    base, last = bases.get(f, (0.0, None))
                    if last is not None and raw < last:
                        # restart: bank what the old process reached, so
                        # base + raw keeps climbing from where it left off
                        base += last
                        was_reset = True
                    bases[f] = (base, raw)
                    if base:
                        adj[f] = base + raw
                out[name] = adj
                if was_reset:
                    resets += 1
        if resets:
            self._registry.counter("telemetry.counter_resets").add(resets)
        return out

    def forget(self, rank: Any) -> None:
        """Drop a rank's baselines (the tracker calls this when a rank
        is admitted fresh under a recycled id, where "lower than before"
        is a new worker, not a restart to re-base)."""
        rk = str(rank)
        with self._lock:
            for key in [k for k in self._bases if k[0] == rk]:
                del self._bases[key]


def state_to_snapshot(state: Dict[str, Dict[str, Any]]
                      ) -> Dict[str, Dict[str, Any]]:
    """Make one rank's serialized state renderable: histogram reservoir
    states become quantile snapshots (a merge of one); everything else is
    already in snapshot form."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, s in (state or {}).items():
        if isinstance(s, dict) and s.get("type") == "histogram" \
                and "samples" in s:
            out[name] = Histogram.merge([s])
        elif isinstance(s, dict):
            out[name] = s
    return out


def render_fleet(per_rank: Dict[str, Dict[str, Dict[str, Any]]],
                 own_snapshot: Optional[Dict[str, Dict[str, Any]]] = None,
                 prefix: str = "dmlc") -> str:
    """The tracker's ``/metrics`` page: merged fleet series, then
    per-rank ``rank="N"`` drill-down series, then (optionally) the
    tracker's own registry labeled ``rank="tracker"``."""
    series: List[Tuple[Optional[Dict[str, str]],
                       Dict[str, Dict[str, Any]]]] = []
    series.append((None, merge_states(per_rank)))
    for rank in sorted(per_rank, key=str):
        series.append(({"rank": str(rank)},
                       state_to_snapshot(per_rank[rank])))
    if own_snapshot:
        series.append(({"rank": "tracker"}, own_snapshot))
    return render_series(series, prefix=prefix)
