"""Tracker-side merging of rank-tagged registry states.

Workers push ``MetricsRegistry.state()`` dicts (histograms carry their
reservoir samples) to the tracker over the tracker protocol; this module
folds a ``{rank: state}`` map into one fleet snapshot and renders the
combined ``/metrics`` page: merged series first (unlabeled — the scrape
target for dashboards), then every contributing rank re-rendered with a
``rank="N"`` label for drill-down.

Merge semantics live with the metric classes (``Counter.merge``,
``Histogram.merge`` over serialized reservoirs, ...); this module only
groups by name/type and skips conflicting types rather than guessing.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..utils.metrics import (Counter, Gauge, Histogram, StageTimer,
                             ThroughputMeter)
from .exposition import render_series

__all__ = ["merge_states", "state_to_snapshot", "render_fleet"]

_MERGERS = {
    "counter": Counter.merge,
    "gauge": Gauge.merge,
    "histogram": Histogram.merge,
    "throughput": ThroughputMeter.merge,
    "stage": StageTimer.merge,
}


def merge_states(per_rank: Dict[str, Dict[str, Dict[str, Any]]]
                 ) -> Dict[str, Dict[str, Any]]:
    """``{rank: {metric_name: state}}`` → merged snapshot-form dict.

    A metric name reported with different types by different ranks (a
    version skew symptom) is dropped from the merged view — the per-rank
    sections still show both sides of the skew.
    """
    by_name: Dict[str, List[Dict[str, Any]]] = {}
    for state in per_rank.values():
        for name, s in (state or {}).items():
            if isinstance(s, dict):
                by_name.setdefault(name, []).append(s)
    merged: Dict[str, Dict[str, Any]] = {}
    for name, states in sorted(by_name.items()):
        types = {s.get("type") for s in states}
        if len(types) != 1:
            continue
        merger = _MERGERS.get(next(iter(types)))
        if merger is not None:
            merged[name] = merger(states)
    return merged


def state_to_snapshot(state: Dict[str, Dict[str, Any]]
                      ) -> Dict[str, Dict[str, Any]]:
    """Make one rank's serialized state renderable: histogram reservoir
    states become quantile snapshots (a merge of one); everything else is
    already in snapshot form."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, s in (state or {}).items():
        if isinstance(s, dict) and s.get("type") == "histogram" \
                and "samples" in s:
            out[name] = Histogram.merge([s])
        elif isinstance(s, dict):
            out[name] = s
    return out


def render_fleet(per_rank: Dict[str, Dict[str, Dict[str, Any]]],
                 own_snapshot: Optional[Dict[str, Dict[str, Any]]] = None,
                 prefix: str = "dmlc") -> str:
    """The tracker's ``/metrics`` page: merged fleet series, then
    per-rank ``rank="N"`` drill-down series, then (optionally) the
    tracker's own registry labeled ``rank="tracker"``."""
    series: List[Tuple[Optional[Dict[str, str]],
                       Dict[str, Dict[str, Any]]]] = []
    series.append((None, merge_states(per_rank)))
    for rank in sorted(per_rank, key=str):
        series.append(({"rank": str(rank)},
                       state_to_snapshot(per_rank[rank])))
    if own_snapshot:
        series.append(({"rank": "tracker"}, own_snapshot))
    return render_series(series, prefix=prefix)
