"""Deterministic fault injection: named probe sites, env-configured plans.

Until now no failure path in this repo was exercisable deterministically —
robustness claims ("the loader rides over worker churn") were code
comments, not tests.  This module makes every claim testable: hot paths
declare **named probe sites** (``fault_point("s3.request")`` around each
HTTP round trip, ``ingest.recv`` per wire frame, …) and a *plan* decides,
per site, whether to inject an error or added latency.

When no plan is active — ``DMLC_FAULT_SPEC`` unset and nothing installed
— a probe is an exact no-op: one module-global ``None`` check, no
counters, no behavior change.  Production binaries pay nothing.

Spec grammar (``DMLC_FAULT_SPEC`` or :func:`install_faults`)::

    spec    := clause (',' clause)*
    clause  := site (':' key '=' value)*
    site    := probe name, exact or prefix glob ("ingest.*")

    keys:
      error=P       probability per call of raising FaultInjected
                    (an OSError subclass, so retry layers treat it
                    exactly like a dropped connection)
      latency=D     added sleep per call: "50ms", "0.2s", or seconds
      lp=P          probability the latency fires (default 1.0)
      seed=N        RNG seed for this clause (default 0) — a fixed seed
                    replays the identical fault schedule every run
      times=N       stop injecting ERRORS after N have fired (the
                    "fail twice, then heal" shape chaos tests need)
      after=N       skip the first N calls before the clause arms
                    (deterministic mid-stream kills)

Example::

    DMLC_FAULT_SPEC='s3.request:error=0.2:seed=7,ingest.recv:latency=50ms'

Each injected error bumps ``faults.<site>.errors``; each injected delay
bumps ``faults.<site>.delays`` — so a chaos test can assert both that
faults actually fired and that the layer under test absorbed them.
"""

from __future__ import annotations

import contextlib
import os
import random
import sys
import threading
import time
from typing import Dict, Iterator, List, Optional

from .logging import DMLCError
from .metrics import metrics
from .parameter import get_env

__all__ = ["FaultInjected", "FaultSpecError", "fault_point",
           "install_faults", "clear_faults", "inject_faults",
           "active_spec"]

ENV_VAR = "DMLC_FAULT_SPEC"


class FaultInjected(OSError):
    """Injected failure.  Subclasses ``OSError`` deliberately: every
    network layer in the repo already treats ``OSError`` as "connection
    trouble, maybe retry", so probes compose with real error handling
    instead of needing their own except-arms."""


class FaultSpecError(DMLCError):
    """Malformed ``DMLC_FAULT_SPEC`` — raised at parse time, loudly: a
    chaos run with a typo'd spec must not silently test nothing."""


def _parse_duration(text: str) -> float:
    t = text.strip().lower()
    try:
        if t.endswith("ms"):
            return float(t[:-2]) / 1e3
        if t.endswith("s"):
            return float(t[:-1])
        return float(t)
    except ValueError:
        raise FaultSpecError(f"bad duration {text!r}") from None


class _Rule:
    """One compiled clause; owns a seeded RNG and its fire counters."""

    __slots__ = ("site", "error_p", "latency_s", "latency_p", "times",
                 "after", "_rng", "_calls", "_fired", "_lock")

    def __init__(self, site: str, error_p: float, latency_s: float,
                 latency_p: float, times: Optional[int], after: int,
                 seed: int) -> None:
        self.site = site
        self.error_p = error_p
        self.latency_s = latency_s
        self.latency_p = latency_p
        self.times = times
        self.after = after
        self._rng = random.Random(seed)
        self._calls = 0
        self._fired = 0
        self._lock = threading.Lock()

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site

    def fire(self, site: str) -> None:
        with self._lock:
            self._calls += 1
            if self._calls <= self.after:
                return
            delay = 0.0
            if self.latency_s > 0 and (self.latency_p >= 1.0
                                       or self._rng.random() < self.latency_p):
                delay = self.latency_s
            raise_error = False
            if self.error_p > 0 and (self.times is None
                                     or self._fired < self.times):
                if self.error_p >= 1.0 or self._rng.random() < self.error_p:
                    raise_error = True
                    self._fired += 1
        if delay > 0:
            metrics.counter(f"faults.{site}.delays").add(1)
            time.sleep(delay)
        if raise_error:
            metrics.counter(f"faults.{site}.errors").add(1)
            # tell the flight recorder (sys.modules — faults never imports
            # telemetry) so chaos runs leave the same evidence trail a
            # real incident would
            fl = sys.modules.get("dmlc_core_tpu.telemetry.flight")
            if fl is not None:
                try:
                    fl.note_fault(site)
                except Exception:
                    pass    # the black box must never mask the fault
            raise FaultInjected(f"injected fault at {site!r}")


class _Plan:
    __slots__ = ("spec", "rules")

    def __init__(self, spec: str, rules: List[_Rule]) -> None:
        self.spec = spec
        self.rules = rules

    def fire(self, site: str) -> None:
        for rule in self.rules:
            if rule.matches(site):
                rule.fire(site)


def _compile(spec: str) -> _Plan:
    rules: List[_Rule] = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        site = parts[0].strip()
        if not site:
            raise FaultSpecError(f"clause {clause!r} has no site name")
        kv: Dict[str, str] = {}
        for p in parts[1:]:
            if "=" not in p:
                raise FaultSpecError(f"bad key=value {p!r} in {clause!r}")
            k, v = p.split("=", 1)
            kv[k.strip()] = v.strip()
        unknown = set(kv) - {"error", "latency", "lp", "seed", "times",
                             "after"}
        if unknown:
            raise FaultSpecError(
                f"unknown keys {sorted(unknown)} in clause {clause!r}")
        try:
            rules.append(_Rule(
                site,
                error_p=float(kv.get("error", 0.0)),
                latency_s=_parse_duration(kv["latency"])
                if "latency" in kv else 0.0,
                latency_p=float(kv.get("lp", 1.0)),
                times=int(kv["times"]) if "times" in kv else None,
                after=int(kv.get("after", 0)),
                seed=int(kv.get("seed", 0))))
        except ValueError as e:
            raise FaultSpecError(f"bad value in clause {clause!r}: {e}") \
                from None
    if not rules:
        raise FaultSpecError(f"empty fault spec {spec!r}")
    return _Plan(spec, rules)


# -- plan lifecycle ----------------------------------------------------------
# _plan is the single hot-path global.  _env_seen tracks the last raw env
# string we compiled, so tests that flip DMLC_FAULT_SPEC (monkeypatch.setenv)
# take effect on the next probe without an explicit install call.

_plan: Optional[_Plan] = None
_env_seen: Optional[str] = None
_explicit = False           # install_faults() wins over the env var
_lifecycle_lock = threading.Lock()


def install_faults(spec: str) -> None:
    """Compile and activate a plan, overriding ``DMLC_FAULT_SPEC``."""
    global _plan, _explicit
    plan = _compile(spec)
    with _lifecycle_lock:
        _plan = plan
        _explicit = True


def clear_faults() -> None:
    """Deactivate any plan (explicit or env-derived)."""
    global _plan, _env_seen, _explicit
    with _lifecycle_lock:
        _plan = None
        _env_seen = None
        _explicit = False


def active_spec() -> Optional[str]:
    """The spec string currently armed, or None."""
    _refresh_from_env()
    p = _plan
    return p.spec if p is not None else None


@contextlib.contextmanager
def inject_faults(spec: str) -> Iterator[None]:
    """Scoped plan for tests: ``with inject_faults("x:error=1:times=1")``."""
    install_faults(spec)
    try:
        yield
    finally:
        clear_faults()


def _refresh_from_env() -> None:
    global _plan, _env_seen
    if _explicit:
        return
    raw = get_env(ENV_VAR, None) or None
    if raw == _env_seen:
        return
    with _lifecycle_lock:
        if _explicit or raw == _env_seen:
            return
        _plan = _compile(raw) if raw else None
        _env_seen = raw


def fault_point(site: str) -> None:
    """Declare a probe site.  No active plan → exact no-op (the fast path
    is one global read + one dict lookup for the env check); active plan →
    matching clauses may sleep and/or raise :class:`FaultInjected`."""
    _refresh_from_env()
    plan = _plan
    if plan is None:
        return
    plan.fire(site)
