"""``key=value`` config-file parser — capability parity with reference
``include/dmlc/config.h`` + ``src/config.cc``.

Reference semantics (`config.h:40-160`, tokenizer `src/config.cc:30-170`):

* ``key = value`` pairs, whitespace-insensitive around ``=``;
* ``#`` starts a comment to end-of-line;
* values may be double-quoted strings with escapes (``\\n``, ``\\t``, ``\\\"``,
  ``\\\\``) — quotes are stripped on read and re-added by ``ToProtoString``;
* *multi-value mode*: when enabled, repeated keys accumulate instead of
  overwriting (`config.h:46-52`); order of insertion is preserved either way;
* ``ToProtoString`` re-emits the config as ``key=value\\n`` lines
  (`config.h:102`).
"""

from __future__ import annotations

import io as _io
from typing import Any, Dict, Iterator, List, TextIO, Tuple, Union

from .logging import DMLCError

__all__ = ["Config"]

_ESCAPES = {"n": "\n", "t": "\t", '"': '"', "\\": "\\", "r": "\r"}
_REV_ESCAPES = {"\n": "\\n", "\t": "\\t", '"': '\\"', "\\": "\\\\", "\r": "\\r"}


def _tokenize(text: str) -> Iterator[Tuple[str, bool]]:
    """Yield (token, was_quoted) skipping comments (reference Tokenizer `src/config.cc:30`)."""
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "#":
            while i < n and text[i] != "\n":
                i += 1
        elif c == '"':
            i += 1
            buf: List[str] = []
            closed = False
            while i < n:
                c = text[i]
                if c == "\\" and i + 1 < n:
                    buf.append(_ESCAPES.get(text[i + 1], text[i + 1]))
                    i += 2
                    continue
                if c == '"':
                    closed = True
                    i += 1
                    break
                buf.append(c)
                i += 1
            if not closed:
                raise DMLCError("Config: unterminated quoted string")
            yield "".join(buf), True
        elif c == "=":
            i += 1
            yield "=", False
        elif c.isspace():
            i += 1
        else:
            j = i
            while j < n and not text[j].isspace() and text[j] not in ('=', '#', '"'):
                j += 1
            yield text[i:j], False
            i = j


class Config:
    """Ordered key→value config (reference ``Config`` `config.h:40`)."""

    def __init__(self, source: Union[str, TextIO, None] = None,
                 multi_value: bool = False):
        self.multi_value = multi_value
        # insertion-ordered list of (key, value_str); _index maps key -> positions
        self._items: List[Tuple[str, str]] = []
        self._index: Dict[str, List[int]] = {}
        if source is not None:
            self.load(source)

    # -- parsing --
    def load(self, source: Union[str, TextIO]) -> None:
        text = source if isinstance(source, str) else source.read()
        toks = list(_tokenize(text))
        i = 0
        while i < len(toks):
            key, key_q = toks[i]
            if key == "=" and not key_q:
                raise DMLCError("Config: unexpected '='")
            if i + 1 >= len(toks) or toks[i + 1][0] != "=" or toks[i + 1][1]:
                raise DMLCError(f"Config: expected '=' after key {key!r}")
            if i + 2 >= len(toks):
                raise DMLCError(f"Config: missing value for key {key!r}")
            val, _ = toks[i + 2]
            self.set_param(key, val)
            i += 3

    # -- mutation (reference SetParam `config.h:81`) --
    def set_param(self, key: str, value: Any) -> None:
        sval = _to_str(value)
        if not self.multi_value and key in self._index:
            self._items[self._index[key][-1]] = (key, sval)
            return
        self._index.setdefault(key, []).append(len(self._items))
        self._items.append((key, sval))

    # -- access (reference GetParam `config.h:89`) --
    def get_param(self, key: str) -> str:
        if key not in self._index:
            raise KeyError(f"config key {key!r} not found")
        return self._items[self._index[key][-1]][1]

    def get_all(self, key: str) -> List[str]:
        return [self._items[i][1] for i in self._index.get(key, [])]

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def __getitem__(self, key: str) -> str:
        return self.get_param(key)

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        """Iterate (key, value) in insertion order (reference iterator `config.h:120`)."""
        return iter(self._items)

    def items(self) -> List[Tuple[str, str]]:
        return list(self._items)

    def to_dict(self) -> Dict[str, str]:
        return {k: v for k, v in self._items}

    # -- output (reference ToProtoString `config.h:102`) --
    def to_proto_string(self) -> str:
        out = _io.StringIO()
        for k, v in self._items:
            if any(ch in v for ch in ' \t\n\r"#=') or v == "":
                v = '"' + "".join(_REV_ESCAPES.get(c, c) for c in v) + '"'
            out.write(f"{k} = {v}\n")
        return out.getvalue()


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)
