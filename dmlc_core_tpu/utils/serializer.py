"""Binary serialization of scalars / containers / numpy arrays to streams —
capability parity with reference ``include/dmlc/serializer.h`` + the typed
``Stream::Read/Write<T>`` surface (`io.h:428-435`).

The reference dispatches at compile time over POD / STL containers /
``Save(Stream)``-classes (`serializer.h:35-120`) with endian awareness
(``DMLC_IO_NO_ENDIAN_SWAP``, `endian.h`).  The TPU-native design fixes the wire
format to **little-endian** (canonical for both x86 hosts and TPU VMs) and
dispatches dynamically:

* fixed-width scalar helpers (``write_uint32`` …) for protocol code,
* :func:`save` / :func:`load` for typed round trips of arbitrary compositions
  of scalars, str/bytes, list/tuple/set/dict, None, numpy arrays, and any
  object exposing ``save(stream)`` / ``load(stream)`` (reference
  ``Serializable`` `io.h:112`, ``SaveLoadClassHandler`` `serializer.h:81`).

``load`` is *schema-free*: values are self-describing via a 1-byte type tag,
unlike the reference where the static type drives decoding.  A ``spec``
argument can assert the expected top-level type.
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO, Optional

import numpy as np

from .logging import DMLCError

__all__ = [
    "save", "load",
    "write_uint32", "read_uint32", "write_uint64", "read_uint64",
    "write_int64", "read_int64", "write_float64", "read_float64",
    "write_bytes", "read_bytes", "write_string", "read_string",
]


# ---- fixed-width scalar helpers (little-endian wire format) ----

def write_uint32(s: Any, v: int) -> None:
    s.write(struct.pack("<I", v))


def write_uint64(s: Any, v: int) -> None:
    s.write(struct.pack("<Q", v))


def write_int64(s: Any, v: int) -> None:
    s.write(struct.pack("<q", v))


def write_float64(s: Any, v: float) -> None:
    s.write(struct.pack("<d", v))


def write_bytes(s: Any, b: bytes) -> None:
    s.write(b)


def _read_exact(s: Any, n: int) -> bytes:
    b = s.read(n)
    if len(b) != n:
        raise DMLCError(f"unexpected EOF: wanted {n} bytes, got {len(b)}")
    return b


def read_uint32(s: Any) -> int:
    return struct.unpack("<I", _read_exact(s, 4))[0]


def read_uint64(s: Any) -> int:
    return struct.unpack("<Q", _read_exact(s, 8))[0]


def read_int64(s: Any) -> int:
    return struct.unpack("<q", _read_exact(s, 8))[0]


def read_float64(s: Any) -> float:
    return struct.unpack("<d", _read_exact(s, 8))[0]


def read_bytes(s: Any, n: int) -> bytes:
    return _read_exact(s, n)


def write_string(s: Any, text: str) -> None:
    """Length-prefixed UTF-8 (reference string handler `serializer.h:125-140`)."""
    b = text.encode("utf-8")
    write_uint64(s, len(b))
    s.write(b)


def read_string(s: Any) -> str:
    n = read_uint64(s)
    return _read_exact(s, n).decode("utf-8")


# ---- tagged self-describing object serialization ----

_T_NONE = 0
_T_BOOL = 1
_T_INT = 2
_T_FLOAT = 3
_T_STR = 4
_T_BYTES = 5
_T_LIST = 6
_T_TUPLE = 7
_T_SET = 8
_T_DICT = 9
_T_NDARRAY = 10
_T_SAVELOAD = 11
_T_BIGINT = 12

_INT64_MIN, _INT64_MAX = -(2**63), 2**63 - 1


def save(s: Any, obj: Any) -> None:
    """Serialize ``obj`` to stream ``s`` (reference ``Stream::Write<T>`` `io.h:428`)."""
    if obj is None:
        s.write(bytes([_T_NONE]))
    elif isinstance(obj, bool):
        s.write(bytes([_T_BOOL, 1 if obj else 0]))
    elif isinstance(obj, int):
        if _INT64_MIN <= obj <= _INT64_MAX:
            s.write(bytes([_T_INT]))
            write_int64(s, obj)
        else:
            # arbitrary-precision fallback: sign byte + length-prefixed magnitude
            b = abs(obj).to_bytes((abs(obj).bit_length() + 7) // 8, "little")
            s.write(bytes([_T_BIGINT, 1 if obj < 0 else 0]))
            write_uint64(s, len(b))
            s.write(b)
    elif isinstance(obj, float):
        s.write(bytes([_T_FLOAT]))
        write_float64(s, obj)
    elif isinstance(obj, str):
        s.write(bytes([_T_STR]))
        write_string(s, obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        s.write(bytes([_T_BYTES]))
        write_uint64(s, len(b))
        s.write(b)
    elif isinstance(obj, list):
        s.write(bytes([_T_LIST]))
        write_uint64(s, len(obj))
        for x in obj:
            save(s, x)
    elif isinstance(obj, tuple):
        s.write(bytes([_T_TUPLE]))
        write_uint64(s, len(obj))
        for x in obj:
            save(s, x)
    elif isinstance(obj, (set, frozenset)):
        s.write(bytes([_T_SET]))
        write_uint64(s, len(obj))
        # deterministic ordering for byte-stable output
        for x in sorted(obj, key=repr):
            save(s, x)
    elif isinstance(obj, dict):
        s.write(bytes([_T_DICT]))
        write_uint64(s, len(obj))
        for k, v in obj.items():
            save(s, k)
            save(s, v)
    elif isinstance(obj, np.ndarray):
        if obj.dtype.hasobject:
            raise DMLCError(
                "cannot serialize object-dtype ndarray; convert to a POD dtype first")
        # contiguous little-endian payload: dtype-str, ndim, shape, raw bytes
        arr = np.ascontiguousarray(obj)
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        s.write(bytes([_T_NDARRAY]))
        write_string(s, arr.dtype.str)
        write_uint32(s, arr.ndim)
        for d in arr.shape:
            write_uint64(s, d)
        write_uint64(s, arr.nbytes)
        s.write(arr.tobytes())
    elif hasattr(obj, "save") and callable(obj.save):
        # Serializable classes (reference io.h:112, serializer.h:81): type must
        # be reconstructible by the caller; we store the class path for checking.
        s.write(bytes([_T_SAVELOAD]))
        write_string(s, f"{type(obj).__module__}.{type(obj).__qualname__}")
        obj.save(s)
    else:
        raise DMLCError(f"cannot serialize object of type {type(obj).__name__}")


def load(s: Any, obj: Any = None) -> Any:
    """Deserialize one value.  If ``obj`` is given and the tag is SAVELOAD,
    loads into ``obj`` via ``obj.load(stream)`` and returns it."""
    tag = _read_exact(s, 1)[0]
    if tag == _T_NONE:
        return None
    if tag == _T_BOOL:
        return _read_exact(s, 1)[0] != 0
    if tag == _T_INT:
        return read_int64(s)
    if tag == _T_FLOAT:
        return read_float64(s)
    if tag == _T_STR:
        return read_string(s)
    if tag == _T_BYTES:
        return _read_exact(s, read_uint64(s))
    if tag in (_T_LIST, _T_TUPLE, _T_SET):
        n = read_uint64(s)
        items = [load(s) for _ in range(n)]
        if tag == _T_TUPLE:
            return tuple(items)
        if tag == _T_SET:
            return set(items)
        return items
    if tag == _T_DICT:
        n = read_uint64(s)
        out = {}
        for _ in range(n):
            k = load(s)
            out[k] = load(s)
        return out
    if tag == _T_NDARRAY:
        dtype = np.dtype(read_string(s))
        ndim = read_uint32(s)
        shape = tuple(read_uint64(s) for _ in range(ndim))
        nbytes = read_uint64(s)
        return np.frombuffer(_read_exact(s, nbytes), dtype=dtype).reshape(shape).copy()
    if tag == _T_BIGINT:
        neg = _read_exact(s, 1)[0] != 0
        n = read_uint64(s)
        v = int.from_bytes(_read_exact(s, n), "little")
        return -v if neg else v
    if tag == _T_SAVELOAD:
        cls_path = read_string(s)
        if obj is None:
            raise DMLCError(
                f"stream holds a Serializable of type {cls_path}; pass an "
                f"instance via load(stream, obj) to receive it")
        obj.load(s)
        return obj
    raise DMLCError(f"corrupt stream: unknown type tag {tag}")
