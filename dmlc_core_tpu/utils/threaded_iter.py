"""Threaded producer→consumer iterator — capability parity with reference
``include/dmlc/threadediter.h``.

The reference ``ThreadedIter<DType>`` (`threadediter.h:46`) runs a single
producer thread filling a bounded queue of heap cells, with a free-cell
recycling list so steady-state allocation is zero, a ``BeforeFirst`` reset
protocol (signals kProduce/kBeforeFirst/kDestroy `threadediter.h:198`,
producer loop :290-357), ``Next(DType**)`` :360 and ``Recycle`` :385.
Exceptions thrown by the producer are captured and re-thrown to the consumer
(`threadediter.h:95-135`).

This implementation keeps the exact contract (bounded queue, recycling,
mid-stream destruction, BeforeFirst reset, producer-exception propagation) on
Python threads.  It is the backbone of the ingest pipeline: chunk prefetch
(io.threaded_split), parse prefetch (data.parser) and the device feed
(pipeline.device_loader) all wrap their producers in it, mirroring how the
reference composes `threaded_input_split.h:23` and `parser.h:71`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Generic, Iterator, List, Optional, TypeVar

from .logging import DMLCError

__all__ = ["ThreadedIter"]

T = TypeVar("T")


class ThreadedIter(Generic[T]):
    """Background producer with bounded queue and cell recycling.

    Parameters
    ----------
    max_capacity:
        Bound on queued items (reference ``set_max_capacity``; chunk wrapper
        uses 2 `threaded_input_split.h:33`, parser uses 8 `parser.h:75`).
    """

    def __init__(self, max_capacity: int = 8):
        self.max_capacity = max(1, int(max_capacity))
        self._lock = threading.Condition()
        self._queue: List[T] = []
        self._free: List[T] = []
        self._produced_end = False
        self._consumed_end = False
        self._destroy = False
        self._reset_pending = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._next_fn: Optional[Callable[[Optional[T]], Optional[T]]] = None
        self._beforefirst_fn: Optional[Callable[[], None]] = None

    # -- setup (reference Init `threadediter.h:282`) --
    def init(self, next_fn: Callable[[Optional[T]], Optional[T]],
             beforefirst_fn: Optional[Callable[[], None]] = None) -> None:
        """Start the producer thread.

        ``next_fn(reuse_cell)`` must return the next item (it *may* reuse and
        return ``reuse_cell``, which is a previously recycled item, to avoid
        allocation) or ``None`` at end-of-stream.  ``beforefirst_fn()`` resets
        the underlying source to the beginning.
        """
        if self._thread is not None:
            raise DMLCError("ThreadedIter.init called twice")
        self._next_fn = next_fn
        self._beforefirst_fn = beforefirst_fn
        self._thread = threading.Thread(target=self._producer_loop, daemon=True)
        self._thread.start()

    @classmethod
    def from_iterable_factory(cls, factory: Callable[[], Iterator[T]],
                              max_capacity: int = 8) -> "ThreadedIter[T]":
        """Convenience: wrap a restartable iterable (factory called per epoch)."""
        it = cls(max_capacity=max_capacity)
        state = {"iter": factory()}

        def next_fn(_cell: Optional[T]) -> Optional[T]:
            try:
                return next(state["iter"])
            except StopIteration:
                return None

        def beforefirst_fn() -> None:
            state["iter"] = factory()

        it.init(next_fn, beforefirst_fn)
        return it

    # -- producer side --
    def _producer_loop(self) -> None:
        while True:
            with self._lock:
                # wait for: destroy | reset request | space to produce
                while (not self._destroy and not self._reset_pending
                       and (self._produced_end or len(self._queue) >= self.max_capacity)):
                    self._lock.wait()
                if self._destroy:
                    return
                if self._reset_pending:
                    # drain queue into free list, reset source, ack consumer
                    # (reference kBeforeFirst handling `threadediter.h:313-328`)
                    self._free.extend(self._queue)
                    self._queue.clear()
                    try:
                        if self._beforefirst_fn is not None:
                            self._beforefirst_fn()
                        self._produced_end = False
                        self._consumed_end = False
                        self._error = None
                    except BaseException as e:  # noqa: BLE001
                        self._error = e
                        self._produced_end = True
                    self._reset_pending = False
                    self._lock.notify_all()
                    continue
                cell = self._free.pop() if self._free else None
            # produce outside the lock (reference calls producer_->Next
            # without holding the mutex, `threadediter.h:330-340`)
            try:
                item = self._next_fn(cell)  # type: ignore[misc]
            except BaseException as e:  # noqa: BLE001
                with self._lock:
                    self._error = e
                    self._produced_end = True
                    self._lock.notify_all()
                continue
            with self._lock:
                if self._reset_pending or self._destroy:
                    # a reset raced with production: drop the item into free
                    if item is not None:
                        self._free.append(item)
                    continue
                if item is None:
                    if cell is not None:
                        self._free.append(cell)
                    self._produced_end = True
                else:
                    self._queue.append(item)
                self._lock.notify_all()

    # -- consumer side --
    def next(self) -> Optional[T]:
        """Pop the next item, or None at end (reference Next `threadediter.h:360-382`).

        Destroy-aware: a consumer blocked here returns None when
        :meth:`destroy` fires, so chained stages (a downstream producer
        thread consuming an upstream iter) unwind cleanly instead of
        deadlocking on a dead producer."""
        with self._lock:
            if self._consumed_end:
                return None
            while (not self._queue and not self._produced_end
                   and not self._destroy):
                self._lock.wait()
            if self._destroy and not self._queue:
                self._consumed_end = True
                return None
            if self._error is not None:
                err = self._error
                self._consumed_end = True
                raise DMLCError(f"ThreadedIter producer failed: {err!r}") from err
            if self._queue:
                item = self._queue.pop(0)
                self._lock.notify_all()
                return item
            self._consumed_end = True
            return None

    def recycle(self, item: T) -> None:
        """Return a consumed cell for reuse (reference Recycle `threadediter.h:385-394`)."""
        with self._lock:
            self._free.append(item)
            self._lock.notify_all()

    def before_first(self) -> None:
        """Reset to the beginning; blocks until the producer acknowledges
        (reference BeforeFirst `threadediter.h:167-190`)."""
        with self._lock:
            if self._thread is None:
                raise DMLCError("ThreadedIter not initialized")
            self._reset_pending = True
            self._lock.notify_all()
            while self._reset_pending and not self._destroy:
                self._lock.wait()
            self._consumed_end = False

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.next()
            if item is None:
                return
            yield item

    # -- teardown (reference destructor sends kDestroy `threadediter.h:205-215`) --
    def destroy(self) -> None:
        with self._lock:
            self._destroy = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "ThreadedIter[T]":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.destroy()

    def __del__(self) -> None:
        try:
            self.destroy()
        except Exception:
            pass
