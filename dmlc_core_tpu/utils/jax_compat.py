"""JAX version compatibility shims.

The package targets jax >= 0.8 (top-level ``jax.shard_map``, ``check_vma``,
``jax.lax.pcast``); clusters routinely pin older runtimes.  Rather than
refusing to import — which takes the whole control plane (tracker, rabit,
launchers) down with the data-plane modules that actually need the new
APIs — the shims translate where a faithful translation exists and let
call sites degrade per-feature.
"""

from __future__ import annotations

__all__ = ["shard_map", "axis_size"]

try:                                    # jax >= 0.6 exports it top-level
    from jax import shard_map as _shard_map
    _KWARG = "check_vma"
except ImportError:                     # older jax: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map
    _KWARG = "check_rep"


def shard_map(f=None, **kw):
    """``jax.shard_map`` with the replication-check kwarg renamed to
    whatever this jax spells it (``check_vma`` grew out of ``check_rep``;
    same semantics for our always-False usage)."""
    if "check_vma" in kw and _KWARG != "check_vma":
        kw[_KWARG] = kw.pop("check_vma")
    if f is None:
        return lambda g: _shard_map(g, **kw)
    return _shard_map(f, **kw)


def axis_size(axis_name):
    """``jax.lax.axis_size`` where it exists; on older jax fall back to
    ``psum(1, axis)`` — same value, computed collectively."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
