"""Global name→factory registries — capability parity with reference ``include/dmlc/registry.h``.

The reference ``Registry<EntryType>`` (`registry.h:27`) provides per-entry-type
global singletons with ``Find`` (:48), ``__REGISTER__`` (:78), ``AddAlias``
(:62) and list enumeration, plus registration macros
(``DMLC_REGISTRY_REGISTER`` `registry.h:246`).  Entries carry name, description,
arguments and a factory body (``FunctionRegEntryBase`` `registry.h:147`).

TPU-native expression: one :class:`Registry` class; each subsystem obtains its
singleton with ``Registry.get("ParserFactory")``.  Registration is a decorator::

    parser_registry = Registry.get("ParserFactory")

    @parser_registry.register("libsvm", description="sparse libsvm text")
    def create_libsvm_parser(uri, part, nparts, extra):
        ...
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from .logging import DMLCError, check

__all__ = ["Registry", "RegistryEntry"]


class RegistryEntry:
    """Analog of ``FunctionRegEntryBase`` (`registry.h:147`)."""

    def __init__(self, name: str, body: Callable[..., Any],
                 description: str = "", arguments: Optional[List[Dict[str, str]]] = None):
        self.name = name
        self.body = body
        self.description = description
        self.arguments = arguments or []
        self.return_type = ""

    def describe(self, description: str) -> "RegistryEntry":
        self.description = description
        return self

    def add_argument(self, name: str, type_: str, description: str) -> "RegistryEntry":
        self.arguments.append({"name": name, "type": type_, "description": description})
        return self

    def set_return_type(self, t: str) -> "RegistryEntry":
        self.return_type = t
        return self

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.body(*args, **kwargs)


class Registry:
    """Name→entry registry with aliasing (reference ``Registry<E>`` `registry.h:27-100`)."""

    _registries: Dict[str, "Registry"] = {}
    _global_lock = threading.Lock()

    def __init__(self, type_name: str):
        self.type_name = type_name
        self._entries: Dict[str, RegistryEntry] = {}
        self._lock = threading.Lock()

    # -- singleton access (reference per-type `Registry::Get()` `registry.h:230`) --
    @classmethod
    def get(cls, type_name: str) -> "Registry":
        with cls._global_lock:
            reg = cls._registries.get(type_name)
            if reg is None:
                reg = cls._registries[type_name] = Registry(type_name)
            return reg

    @classmethod
    def list_registries(cls) -> List[str]:
        with cls._global_lock:
            return sorted(cls._registries)

    # -- registration --
    def register(self, name: str, description: str = "",
                 allow_override: bool = False) -> Callable[[Callable[..., Any]], Callable[..., Any]]:
        """Decorator registering ``fn`` under ``name`` (reference ``__REGISTER__`` `registry.h:78`)."""

        def deco(fn: Callable[..., Any]) -> Callable[..., Any]:
            self.register_entry(RegistryEntry(name, fn, description), allow_override)
            return fn

        return deco

    def register_entry(self, entry: RegistryEntry, allow_override: bool = False) -> RegistryEntry:
        with self._lock:
            if entry.name in self._entries and not allow_override:
                raise DMLCError(
                    f"{self.type_name} '{entry.name}' is already registered")
            self._entries[entry.name] = entry
            return entry

    def add_alias(self, key_name: str, alias: str) -> None:
        """Register ``alias`` → same entry (reference ``AddAlias`` `registry.h:62-70`)."""
        with self._lock:
            check(key_name in self._entries, f"cannot alias missing entry '{key_name}'")
            if alias in self._entries:
                raise DMLCError(f"{self.type_name} alias '{alias}' already registered")
            self._entries[alias] = self._entries[key_name]

    # -- lookup --
    def find(self, name: str) -> Optional[RegistryEntry]:
        """Reference ``Find`` `registry.h:48-54`: None when absent."""
        with self._lock:
            return self._entries.get(name)

    def __getitem__(self, name: str) -> RegistryEntry:
        entry = self.find(name)
        if entry is None:
            raise KeyError(
                f"unknown {self.type_name} '{name}'; registered: {self.list_names()}")
        return entry

    def __contains__(self, name: str) -> bool:
        return self.find(name) is not None

    def list_names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def remove(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)
