"""In-memory streams — capability parity with reference
``include/dmlc/memory_io.h``.

* :class:`MemoryFixedSizeStream` — read/write over a caller-owned fixed
  buffer (a ``memoryview``/``bytearray``); writing past the end raises, as
  the reference CHECKs (`memory_io.h:21-60`).
* :class:`MemoryStringStream` — growable stream over an owned buffer
  (`memory_io.h:66-103`); ``value`` exposes the bytes written so far.

Both are seekable and satisfy the same duck-typed binary-stream contract
the serializer and RowBlock ``save``/``load`` use, so every Stream consumer
can be unit-tested without touching disk (the reference uses these heavily
in its serializer tests, `unittest_serializer.cc:12-25`).
"""

from __future__ import annotations

import io
import os

from .logging import check

__all__ = ["MemoryFixedSizeStream", "MemoryStringStream"]


class MemoryFixedSizeStream(io.RawIOBase):
    """Stream over a fixed caller buffer (`memory_io.h:21-60`)."""

    def __init__(self, buffer) -> None:
        super().__init__()
        self._buf = memoryview(buffer)
        self._pos = 0

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return not self._buf.readonly

    def seekable(self) -> bool:
        return True

    def tell(self) -> int:
        return self._pos

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_SET:
            new = offset
        elif whence == os.SEEK_CUR:
            new = self._pos + offset
        elif whence == os.SEEK_END:
            new = len(self._buf) + offset
        else:
            raise ValueError(f"bad whence {whence}")
        check(0 <= new <= len(self._buf),
              f"seek {new} outside fixed buffer of {len(self._buf)}")
        self._pos = new
        return self._pos

    def readinto(self, b) -> int:
        n = min(len(b), len(self._buf) - self._pos)
        b[:n] = self._buf[self._pos:self._pos + n]
        self._pos += n
        return n

    def write(self, b) -> int:
        check(not self._buf.readonly, "stream over a readonly buffer")
        # reference CHECKs the write fits (`memory_io.h:38`)
        check(self._pos + len(b) <= len(self._buf),
              f"write of {len(b)} at {self._pos} overflows fixed buffer "
              f"of {len(self._buf)}")
        self._buf[self._pos:self._pos + len(b)] = b
        self._pos += len(b)
        return len(b)


class MemoryStringStream(io.BytesIO):
    """Growable in-memory stream (`memory_io.h:66-103`)."""

    @property
    def value(self) -> bytes:
        return self.getvalue()
