"""Unified resilience primitives: retry/backoff, circuit breaker, deadlines.

The reference survived real clusters because every layer had its own
failure story — S3 streams restart on seek (`s3_filesys.cc:234-239`), the
tracker rebuilds topologies when workers die (`tracker.py:279-291`) — but
each story was hand-rolled in place.  This module is the one shared
implementation the whole repo retries through, so policy (how many
attempts, how long, when to give up) is tunable in one vocabulary and
every retry/open/shed shows up in ``utils.metrics``:

* :class:`Deadline` — a wall-clock budget threaded through nested calls;
  ``remaining()`` caps every sleep and socket timeout below it, so a
  retry loop can never overshoot its caller's patience.
* :class:`RetryPolicy` — bounded attempts with exponential backoff and
  **full jitter** (delay ~ U[0, min(cap, base·2^attempt)]), an optional
  retryable-exception predicate, and a per-call deadline budget.  The
  jitter RNG is seedable so replayed failure schedules are deterministic
  under test (the same property ``utils.faults`` relies on).
* :class:`CircuitBreaker` — closed → open after N consecutive failures,
  half-open probe after a cooldown, re-close on success.  Guards
  reconnect storms: when a dependency is down, failing fast beats
  hammering it with the full retry schedule per caller.

Env knobs (read by :meth:`RetryPolicy.from_env` /
:meth:`CircuitBreaker.from_env`; each subsystem passes its own prefix):

==============================  =============================================
``<PREFIX>_RETRIES``            attempt cap (total tries, not re-tries)
``<PREFIX>_BACKOFF_BASE``       first-retry backoff ceiling, seconds
``<PREFIX>_BACKOFF_MAX``        per-sleep backoff cap, seconds
``<PREFIX>_DEADLINE``           per-call budget, seconds (0 = unbounded)
``<PREFIX>_BREAKER_THRESHOLD``  consecutive failures before the circuit opens
``<PREFIX>_BREAKER_COOLDOWN``   seconds open before a half-open probe
==============================  =============================================
"""

from __future__ import annotations

import math
import random
import sys
import threading
import time
from typing import Any, Callable, Optional

from .logging import DMLCError, log_warning
from .metrics import metrics

__all__ = [
    "Deadline", "DeadlineExpired", "RetryPolicy", "RetriesExhausted",
    "CircuitBreaker", "CircuitOpen",
]


def _trace_event(name: str, **attrs: Any) -> None:
    """Mirror a resilience signal onto the active telemetry span, so a
    Perfetto trace of a slow request shows *why* it was slow.  Looked up
    via sys.modules (never imported here): utils.retry sits below the
    telemetry package in the import graph, and an untraced process pays
    one dict miss."""
    mod = sys.modules.get("dmlc_core_tpu.telemetry.trace")
    if mod is None:
        return
    try:
        mod.add_event(name, **attrs)
    except Exception:   # telemetry must never break the retried call
        pass


class DeadlineExpired(DMLCError):
    """The per-call time budget ran out before the operation succeeded."""


class RetriesExhausted(DMLCError):
    """The attempt cap was reached; the last cause is chained as
    ``__cause__``."""


class CircuitOpen(DMLCError):
    """The breaker is open — the dependency is presumed down; fail fast
    instead of burning a retry schedule against it."""


class Deadline:
    """Wall-clock budget, created once and threaded through nested calls.

    ``Deadline(None)`` (or budget ≤ 0 via :meth:`from_env`) is unbounded:
    ``remaining()`` is ``inf`` and ``expired()`` never fires — callers can
    clamp against it unconditionally.
    """

    __slots__ = ("_t_end", "_clock")

    def __init__(self, budget_s: Optional[float],
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._t_end = None if budget_s is None else clock() + float(budget_s)

    @classmethod
    def unbounded(cls) -> "Deadline":
        return cls(None)

    def remaining(self) -> float:
        if self._t_end is None:
            return math.inf
        return self._t_end - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def clamp(self, timeout_s: float) -> float:
        """Bound a sleep/socket timeout by what's left of the budget."""
        return max(0.0, min(float(timeout_s), self.remaining()))

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise DeadlineExpired(f"{what}: deadline budget exhausted")


def _default_retryable(exc: BaseException) -> bool:
    return isinstance(exc, (OSError, ConnectionError))


class RetryPolicy:
    """Bounded retries with exponential backoff and full jitter.

    ``max_attempts`` counts total tries (1 = no retries).  ``retryable``
    decides which exceptions earn another attempt (default: ``OSError``
    family — the transient-network shape).  ``deadline_s`` bounds the
    whole :meth:`call`, sleeps included; a deadline passed explicitly to
    :meth:`call` takes precedence (it is the caller's budget, shared with
    whatever else the caller does).

    Every retry bumps ``retry.<name>.retries``; giving up bumps
    ``retry.<name>.exhausted`` — visible in any metrics snapshot next to
    the subsystem's own counters.
    """

    def __init__(self, max_attempts: int = 4, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0,
                 deadline_s: Optional[float] = None,
                 retryable: Optional[Callable[[BaseException], bool]] = None,
                 seed: Optional[int] = None, name: str = "default",
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.deadline_s = deadline_s
        self.name = name
        self._retryable = retryable or _default_retryable
        self._rng = random.Random(seed)
        self._sleep = sleep

    @classmethod
    def from_env(cls, prefix: str, *, name: str = "", **kw) -> "RetryPolicy":
        from .parameter import get_env
        kw.setdefault("max_attempts", get_env(f"{prefix}_RETRIES", 4))
        kw.setdefault("base_delay_s", get_env(f"{prefix}_BACKOFF_BASE", 0.05))
        kw.setdefault("max_delay_s", get_env(f"{prefix}_BACKOFF_MAX", 2.0))
        dl = get_env(f"{prefix}_DEADLINE", 0.0)
        kw.setdefault("deadline_s", dl if dl > 0 else None)
        return cls(name=name or prefix.lower(), **kw)

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter delay before try ``attempt + 1`` (attempt is
        1-based: the try that just failed)."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * (2.0 ** max(0, attempt - 1)))
        return self._rng.uniform(0.0, cap)

    def call(self, fn: Callable[..., Any], *args: Any,
             deadline: Optional[Deadline] = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             **kw: Any) -> Any:
        """Run ``fn`` under this policy; returns its result or raises the
        last error (:class:`DeadlineExpired` / :class:`RetriesExhausted`
        wrap it so callers can distinguish budget kinds)."""
        dl = deadline or Deadline(self.deadline_s)
        m_retry = metrics.counter(f"retry.{self.name}.retries")
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kw)
            except BaseException as e:  # noqa: BLE001 — predicate decides
                if not self._retryable(e):
                    raise
                if attempt >= self.max_attempts:
                    metrics.counter(f"retry.{self.name}.exhausted").add(1)
                    _trace_event("retries_exhausted", policy=self.name,
                                 attempts=attempt, error=str(e))
                    raise RetriesExhausted(
                        f"{self.name}: gave up after {attempt} attempts: "
                        f"{e}") from e
                if dl.expired():
                    metrics.counter(f"retry.{self.name}.exhausted").add(1)
                    _trace_event("retries_exhausted", policy=self.name,
                                 attempts=attempt, error=str(e),
                                 reason="deadline")
                    raise DeadlineExpired(
                        f"{self.name}: deadline exhausted after {attempt} "
                        f"attempts: {e}") from e
                m_retry.add(1)
                _trace_event("retry", policy=self.name, attempt=attempt,
                             error=str(e))
                if on_retry is not None:
                    on_retry(attempt, e)
                delay = self.backoff_s(attempt)
                # server-directed backoff (e.g. HTTP Retry-After): an
                # exception carrying retry_after_s raises the floor; the
                # deadline clamp below caps even a hostile hint at the
                # remaining budget
                hint = getattr(e, "retry_after_s", None)
                if hint is not None:
                    delay = max(delay, float(hint))
                self._sleep(dl.clamp(delay))
                if dl.expired():
                    # the (clamped) sleep consumed the rest of the budget;
                    # an attempt now would run with a zero timeout and
                    # mask the real failure behind a bogus transport error
                    metrics.counter(f"retry.{self.name}.exhausted").add(1)
                    raise DeadlineExpired(
                        f"{self.name}: deadline exhausted after {attempt} "
                        f"attempts: {e}") from e


class CircuitBreaker:
    """Consecutive-failure circuit breaker (thread-safe).

    closed → ``record_failure()`` × ``failure_threshold`` → open (every
    ``allow()`` raises :class:`CircuitOpen` for ``cooldown_s``) →
    half-open (ONE caller gets through as the probe) → closed on success,
    re-open on failure.  Opens bump ``circuit.<name>.opens``; fast-fails
    bump ``circuit.<name>.fast_fails``.
    """

    def __init__(self, name: str = "default", failure_threshold: int = 5,
                 cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False

    @classmethod
    def from_env(cls, prefix: str, *, name: str = "", **kw) -> "CircuitBreaker":
        from .parameter import get_env
        kw.setdefault("failure_threshold",
                      get_env(f"{prefix}_BREAKER_THRESHOLD", 5))
        kw.setdefault("cooldown_s", get_env(f"{prefix}_BREAKER_COOLDOWN", 5.0))
        return cls(name=name or prefix.lower(), **kw)

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._clock() - self._opened_at >= self.cooldown_s:
                return "half_open"
            return "open"

    def allow(self) -> None:
        """Gate one attempt: raises :class:`CircuitOpen` while open; in
        half-open admits exactly one probe (others keep fast-failing
        until the probe reports back)."""
        with self._lock:
            if self._opened_at is None:
                return
            if (self._clock() - self._opened_at >= self.cooldown_s
                    and not self._probing):
                self._probing = True        # this caller is the probe
                _trace_event("circuit_probe", circuit=self.name)
                return
            metrics.counter(f"circuit.{self.name}.fast_fails").add(1)
            _trace_event("circuit_fast_fail", circuit=self.name)
            raise CircuitOpen(
                f"circuit {self.name!r} open "
                f"({self._failures} consecutive failures)")

    def record_success(self) -> None:
        with self._lock:
            recovered = self._opened_at is not None
            self._failures = 0
            self._opened_at = None
            self._probing = False
        if recovered:
            # the probe came back: the dependency healed.  Traced so a
            # Perfetto lane shows the open→closed bracket, not just the trip
            _trace_event("circuit_close", circuit=self.name)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._opened_at is not None:
                # failed probe: restart the cooldown
                self._opened_at = self._clock()
            elif self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                metrics.counter(f"circuit.{self.name}.opens").add(1)
                _trace_event("circuit_open", circuit=self.name,
                             failures=self._failures)
                log_warning("circuit %s opened after %d consecutive "
                            "failures", self.name, self._failures)

    def call(self, fn: Callable[..., Any], *args: Any, **kw: Any) -> Any:
        """``allow()`` + run + record; exceptions count as failures."""
        self.allow()
        try:
            out = fn(*args, **kw)
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return out
