"""Concurrency primitives — capability parity with reference
``include/dmlc/concurrency.h`` and ``include/dmlc/thread_local.h``.

* :class:`ConcurrentBlockingQueue` — bounded-or-unbounded MPMC blocking queue
  in FIFO or PRIORITY mode with the reference's ``SignalForKill`` shutdown
  protocol (`concurrency.h:65-253`): after the signal, every blocked ``pop``
  wakes and returns ``None``, and the kill state is sticky until resumed.
* :class:`Spinlock` — busy-wait lock (`concurrency.h:24-60`). In CPython a
  pure spin is rarely right; this implementation spins a bounded number of
  times then parks on a real lock, which matches the reference's intent
  (cheap under low contention) without burning the GIL.
* :class:`ThreadLocalStore` — per-thread singleton store
  (`thread_local.h:35-78`): one instance of a factory per thread, with
  ``clear`` support for tests.
* :class:`ObjectPool` — free-list object pool (reference ``MemoryPool``
  `memory.h:22-80`): recycle expensive buffers (e.g. chunk bytearrays)
  across pipeline iterations instead of reallocating.
"""

from __future__ import annotations

import collections
import heapq
import threading
from typing import Any, Callable, Deque, Dict, Generic, List, Optional, TypeVar

__all__ = ["ConcurrentBlockingQueue", "Spinlock", "ThreadLocalStore",
           "ObjectPool", "FIFO", "PRIORITY"]

T = TypeVar("T")

FIFO = "fifo"
PRIORITY = "priority"


class Spinlock:
    """Bounded spin then park (`concurrency.h:24-60`). Context-manager."""

    __slots__ = ("_lock", "_spins")

    def __init__(self, spins: int = 64) -> None:
        self._lock = threading.Lock()
        self._spins = spins

    def acquire(self) -> None:
        for _ in range(self._spins):
            if self._lock.acquire(blocking=False):
                return
        self._lock.acquire()

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "Spinlock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class ConcurrentBlockingQueue(Generic[T]):
    """MPMC blocking queue, FIFO or priority, with SignalForKill
    (`concurrency.h:65-253`).

    ``push(v)`` blocks while full (bounded mode); ``pop()`` blocks while
    empty; ``signal_for_kill()`` wakes all waiters — blocked ``pop`` returns
    ``None`` and blocked ``push`` returns ``False`` — and stays in effect
    until :meth:`resume`.  Priority mode pops the highest ``priority`` first
    (reference ``Push(v, priority)`` `concurrency.h:103`).
    """

    def __init__(self, max_size: int = 0, policy: str = FIFO) -> None:
        assert policy in (FIFO, PRIORITY)
        self._policy = policy
        self._max = max_size
        self._fifo: Deque[T] = collections.deque()
        self._heap: List[Any] = []
        self._seq = 0                      # FIFO tiebreak within a priority
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._kill = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._fifo) + len(self._heap)

    def _full(self) -> bool:
        return self._max > 0 and (len(self._fifo) + len(self._heap)) >= self._max

    def push(self, value: T, priority: int = 0,
             timeout: Optional[float] = None) -> bool:
        with self._lock:
            while self._full() and not self._kill:
                if not self._not_full.wait(timeout):
                    return False
            if self._kill:
                return False
            if self._policy == FIFO:
                self._fifo.append(value)
            else:
                self._seq += 1
                heapq.heappush(self._heap, (-priority, self._seq, value))
            self._not_empty.notify()
            return True

    def pop(self, timeout: Optional[float] = None) -> Optional[T]:
        with self._lock:
            while not (self._fifo or self._heap) and not self._kill:
                if not self._not_empty.wait(timeout):
                    return None
            if self._kill and not (self._fifo or self._heap):
                return None
            if self._policy == FIFO:
                v = self._fifo.popleft()
            else:
                v = heapq.heappop(self._heap)[2]
            self._not_full.notify()
            return v

    def signal_for_kill(self) -> None:
        """Wake all waiters; queue refuses new work (`concurrency.h:208`)."""
        with self._lock:
            self._kill = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def resume(self) -> None:
        with self._lock:
            self._kill = False

    @property
    def killed(self) -> bool:
        return self._kill


class ThreadLocalStore:
    """Per-thread singleton store (`thread_local.h:35-78`): ``get(factory)``
    returns this thread's instance for that factory, constructing once."""

    _tls = threading.local()

    @classmethod
    def get(cls, factory: Callable[[], T]) -> T:
        store: Dict[Any, Any] = getattr(cls._tls, "store", None)
        if store is None:
            store = {}
            cls._tls.store = store
        key = factory
        if key not in store:
            store[key] = factory()
        return store[key]

    @classmethod
    def clear(cls) -> None:
        cls._tls.store = {}


class ObjectPool(Generic[T]):
    """Free-list pool for reusable buffers (reference ``MemoryPool``
    `memory.h:22-80`; same recycling idea as ``ThreadedIter::Recycle``
    `threadediter.h:385`)."""

    def __init__(self, factory: Callable[[], T], max_free: int = 16) -> None:
        self._factory = factory
        self._free: List[T] = []
        self._max_free = max_free
        self._lock = threading.Lock()

    def acquire(self) -> T:
        with self._lock:
            if self._free:
                return self._free.pop()
        return self._factory()

    def release(self, obj: T) -> None:
        with self._lock:
            if len(self._free) < self._max_free:
                self._free.append(obj)

    def __enter__(self):
        raise TypeError("use pool.acquire()/release(), not a context manager")
