"""Declarative typed hyper-parameter system — capability parity with reference
``include/dmlc/parameter.h``.

The reference provides ``Parameter<PType>`` structs with declared fields
carrying defaults, ranges, enums, aliases, docstring generation, env-var reads
and JSON save/load (`parameter.h:122-238`, ``DMLC_DECLARE_FIELD``
`parameter.h:268`, ``DMLC_DECLARE_ALIAS`` :275, ``FieldEntryNumeric::set_range``
:660, ``FieldEntry<int>::add_enum`` :761, ``GetEnv`` :46).  Bad values raise
``ParamError`` (`parameter.h:62`).

TPU-native expression: a metaclass-driven ``Parameter`` base class with
``field()`` descriptors::

    class CSVParserParam(Parameter):
        format = field(str, default="csv")
        label_column = field(int, default=-1, help="column id of the label")

    p = CSVParserParam()
    unknown = p.init({"label_column": 0, "x": 1}, allow_unknown=True)

Capabilities: defaults, required fields, [lo, hi] ranges, enum domains
(string-or-value), aliases, ``init``/``init_allow_unknown``, ``to_dict``
(``__DICT__`` :176), ``save_json``/``load_json`` (:185-197), ``fields()``
(``__FIELDS__`` :202), ``doc_string()`` (``PrintDocString`` :483), and
``update_dict`` env-var style overlays.  ``get_env`` mirrors ``GetEnv``.
"""

from __future__ import annotations

import copy
import json
import math
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple, Type, Union

from .logging import ParamError

__all__ = ["Parameter", "field", "FieldEntry", "get_env", "env_int",
           "parse_lenient_bool"]

_NOTHING = object()


def _parse_bool(s: Any) -> bool:
    """Boolean parse accepting true/false/1/0 (reference ``FieldEntry<bool>`` `parameter.h:795-820`)."""
    if isinstance(s, bool):
        return s
    if isinstance(s, (int, float)):
        return bool(s)
    t = str(s).strip().lower()
    if t in ("true", "1", "yes", "t"):
        return True
    if t in ("false", "0", "no", "f"):
        return False
    raise ValueError(f"invalid bool value {s!r}")


class FieldEntry:
    """One declared parameter field (reference ``FieldEntry<T>`` `parameter.h:596+`)."""

    def __init__(self, dtype: Type[Any], default: Any = _NOTHING, *,
                 help: str = "", range: Optional[Tuple[Any, Any]] = None,
                 enum: Optional[Iterable[Any]] = None,
                 aliases: Iterable[str] = (),
                 lower_bound: Any = None, upper_bound: Any = None,
                 optional: bool = False,
                 validate: Optional[Callable[[Any], bool]] = None):
        self.dtype = dtype
        self.default = default
        self.help = help
        self.lower = lower_bound
        self.upper = upper_bound
        if range is not None:
            self.lower, self.upper = range
        # a callable enum is a LAZY domain, re-evaluated at each check:
        # registry-derived choice lists (e.g. the CLI's model enum) must
        # see entries registered after this field's class body ran
        self.enum = (enum if callable(enum) else list(enum)) \
            if enum is not None else None
        self.aliases = list(aliases)
        self.optional = optional
        self.validate = validate
        self.name: str = "<unbound>"

    def __set_name__(self, owner: type, name: str) -> None:
        self.name = name

    # descriptor protocol: instances store values in __dict__ under the field name
    def __get__(self, obj: Any, objtype: type = None) -> Any:
        if obj is None:
            return self
        if self.name in obj.__dict__:
            return obj.__dict__[self.name]
        if self.default is _NOTHING:
            raise AttributeError(f"required parameter '{self.name}' not set")
        if isinstance(self.default, (list, dict, set, bytearray)):
            # materialize a per-instance copy so mutable defaults never alias
            # across instances
            value = copy.copy(self.default)
            obj.__dict__[self.name] = value
            return value
        return self.default

    def __set__(self, obj: Any, value: Any) -> None:
        obj.__dict__[self.name] = self.check_and_convert(value)

    # -- value handling --
    def convert(self, value: Any) -> Any:
        if value is None:
            if self.optional:
                return None
            raise ValueError(f"parameter '{self.name}' cannot be None")
        if self.dtype is bool:
            return _parse_bool(value)
        if self.dtype in (int,):
            # reject silent float truncation like "2.5" -> 2 but allow "3"/"3.0"
            if isinstance(value, str):
                f = float(value)
            elif isinstance(value, float):
                f = value
            else:
                return int(value)
            i = int(f)
            if f != i:
                raise ValueError(f"value {value!r} for int parameter '{self.name}' is not integral")
            return i
        if self.dtype is float:
            f = float(value)
            if math.isnan(f):
                raise ValueError(f"value {value!r} for parameter '{self.name}' is NaN")
            return f
        if self.dtype is str:
            return str(value)
        if isinstance(value, self.dtype):
            return value
        return self.dtype(value)

    def check_and_convert(self, value: Any) -> Any:
        try:
            v = self.convert(value)
        except (TypeError, ValueError, OverflowError) as e:
            raise ParamError(
                f"Invalid value {value!r} for parameter '{self.name}' "
                f"(expect {self.dtype.__name__}): {e}") from None
        if v is None:
            return v
        if self.enum is not None:
            domain = list(self.enum()) if callable(self.enum) else self.enum
            if v not in domain:
                raise ParamError(
                    f"Invalid value {v!r} for parameter '{self.name}': "
                    f"expected one of {domain}")
        # range semantics mirror reference set_range/set_lower_bound: inclusive
        # bounds, violation raises ParamError (`parameter.h:646-700`).
        if self.lower is not None and v < self.lower:
            raise ParamError(
                f"value {v!r} for parameter '{self.name}' is below lower bound {self.lower!r}")
        if self.upper is not None and v > self.upper:
            raise ParamError(
                f"value {v!r} for parameter '{self.name}' exceeds upper bound {self.upper!r}")
        if self.validate is not None and not self.validate(v):
            raise ParamError(f"value {v!r} for parameter '{self.name}' failed validation")
        return v

    @property
    def required(self) -> bool:
        return self.default is _NOTHING

    def doc(self) -> str:
        parts = [f"{self.name} : {self.dtype.__name__}"]
        if self.required:
            parts.append("(required)")
        else:
            parts.append(f"(default={self.default!r})")
        if self.enum is not None:
            parts.append(f"choices="
                         f"{list(self.enum()) if callable(self.enum) else self.enum}")
        if self.lower is not None or self.upper is not None:
            parts.append(f"range=[{self.lower}, {self.upper}]")
        head = " ".join(parts)
        return head + ("\n    " + self.help if self.help else "")


def field(dtype: Type[Any], default: Any = _NOTHING, **kwargs: Any) -> FieldEntry:
    """Declare a parameter field (reference ``DMLC_DECLARE_FIELD`` `parameter.h:268`)."""
    return FieldEntry(dtype, default, **kwargs)


class _ParamMeta(type):
    def __new__(mcls, name: str, bases: Tuple[type, ...], ns: Dict[str, Any]):
        cls = super().__new__(mcls, name, bases, ns)
        entries: Dict[str, FieldEntry] = {}
        alias_map: Dict[str, str] = {}
        for base in reversed(cls.__mro__):
            for k, v in vars(base).items():
                if isinstance(v, FieldEntry):
                    entries[k] = v
        for k, e in entries.items():
            for a in e.aliases:
                alias_map[a] = k
        cls.__param_fields__ = entries
        cls.__param_aliases__ = alias_map
        return cls


class Parameter(metaclass=_ParamMeta):
    """Base class for declarative parameter structs (reference ``Parameter<PType>`` `parameter.h:122`).

    Instances are mutable config structs and therefore intentionally
    **unhashable** (``__eq__`` without ``__hash__``); compare with ``==`` or
    key dicts by ``save_json()``.
    """

    __param_fields__: Dict[str, FieldEntry] = {}
    __param_aliases__: Dict[str, str] = {}

    def __init__(self, **kwargs: Any):
        if kwargs:
            self.init(kwargs)

    # -- init protocol (reference Init `parameter.h:136`, InitAllowUnknown :154) --
    def init(self, kwargs: Dict[str, Any], allow_unknown: bool = False) -> Dict[str, Any]:
        """Set fields from ``kwargs``; returns dict of unknown args.

        Raises :class:`ParamError` on unknown keys (unless ``allow_unknown``),
        bad values, out-of-range values, or missing required fields.
        """
        fields = self.__param_fields__
        aliases = self.__param_aliases__
        unknown: Dict[str, Any] = {}
        for k, v in kwargs.items():
            key = aliases.get(k, k)
            entry = fields.get(key)
            if entry is None:
                if allow_unknown:
                    unknown[k] = v
                    continue
                raise ParamError(
                    f"unknown parameter '{k}' for {type(self).__name__}; "
                    f"candidates: {sorted(fields)}")
            entry.__set__(self, v)
        missing = [k for k, e in fields.items()
                   if e.required and k not in self.__dict__]
        if missing:
            raise ParamError(
                f"required parameters {missing} of {type(self).__name__} not set")
        return unknown

    def init_allow_unknown(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        return self.init(kwargs, allow_unknown=True)

    def update_dict(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        """Update known keys only, return the rest (reference ``UpdateDict`` `parameter.h:166`)."""
        return self.init(kwargs, allow_unknown=True)

    # -- reflection (reference __DICT__ :176, __FIELDS__ :202, __DOC__ :213) --
    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__param_fields__
                if not self.__param_fields__[k].required or k in self.__dict__}

    @classmethod
    def fields(cls) -> List[FieldEntry]:
        return list(cls.__param_fields__.values())

    @classmethod
    def doc_string(cls) -> str:
        lines = [f"Parameters of {cls.__name__}", "-" * 30]
        for e in cls.__param_fields__.values():
            lines.append(e.doc())
        return "\n".join(lines)

    # -- JSON round trip (reference Save/Load `parameter.h:185-197`) --
    def save_json(self) -> str:
        return json.dumps({k: v for k, v in self.to_dict().items()}, sort_keys=True)

    def load_json(self, s: str) -> None:
        self.init(json.loads(s), allow_unknown=False)

    def save(self, stream: Any) -> None:
        """Serialize as JSON text to a Stream (duck-typed ``.write``)."""
        data = self.save_json().encode("utf-8")
        from .serializer import write_uint64, write_bytes
        write_uint64(stream, len(data))
        write_bytes(stream, data)

    def load(self, stream: Any) -> None:
        from .serializer import read_uint64, read_bytes
        n = read_uint64(stream)
        self.load_json(read_bytes(stream, n).decode("utf-8"))

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self.to_dict().items())
        return f"{type(self).__name__}({inner})"

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.to_dict() == other.to_dict()


# env keys already warned about, so a malformed value logs ONE warning
# per process instead of one per worker-thread read
_env_warned: set = set()


def env_int(key: str, default: int, *, minimum: Optional[int] = None) -> int:
    """Lenient integer env read for knobs parsed on worker hot paths.

    Unlike :func:`get_env`, a malformed value never raises: it logs one
    WARNING (per key, per process) and falls back to ``default`` — a
    typo'd ``DMLC_PAGE_CACHE_QUEUE=8x`` must degrade the knob, not kill
    the first loader thread that reads it.  ``minimum`` clamps the
    parsed value (the clamp is silent: a deliberate 0 meaning "off"
    should use ``minimum=None``)."""
    raw = os.environ.get(key)
    if raw is None or not raw.strip():
        return default
    try:
        v = int(raw)
    except ValueError:
        if key not in _env_warned:
            _env_warned.add(key)
            from .logging import log_warning
            log_warning("ignoring malformed %s=%r (want an integer); "
                        "using default %r", key, raw, default)
        return default
    return v if minimum is None else max(minimum, v)


def parse_lenient_bool(key: str) -> Optional[bool]:
    """Lenient boolean env read: None when unset, the parsed value when
    well-formed, None + one WARNING when malformed (same contract as
    :func:`env_int` — never raise from a knob read)."""
    raw = os.environ.get(key)
    if raw is None or not raw.strip():
        return None
    try:
        return _parse_bool(raw)
    except Exception:
        if key not in _env_warned:
            _env_warned.add(key)
            from .logging import log_warning
            log_warning("ignoring malformed %s=%r (want true/false/1/0)",
                        key, raw)
        return None


def get_env(key: str, default: Any) -> Any:
    """Typed env read (reference ``GetEnv`` `parameter.h:46,1034+`).

    The returned value is converted to ``type(default)`` (bools accept
    true/false/1/0).
    """
    raw = os.environ.get(key)
    if raw is None:
        return default
    t = type(default)
    if t is bool:
        return _parse_bool(raw)
    if default is None:
        return raw
    return t(raw)
