"""Durable-state substrate: fsync'd WAL + atomic snapshots + fenced leases.

Generalization of the data-service dispatcher journal (PR 16) into the
single substrate every control-plane singleton journals through — the
dispatcher, the serving-fleet ``ReplicaRegistry``, and the
``RabitTracker``.  The tf.data service papers (PAPERS.md: arxiv
2210.14826, 2101.12127) make the journaled coordinator the precondition
for disaggregation; the same argument applies to every coordinator in
this tree, so the mechanics live here once:

* ``<prefix>.log`` — append-only JSON-lines, each line fsync'd *before*
  the caller's in-memory mutation proceeds (write-ahead ordering).  A
  torn tail (crash inside a write) is tolerated by stopping replay at
  the first undecodable line.
* ``<prefix>.snap`` — the full state as one JSON document, written with
  the page-cache crash-safety idiom (``.tmp.<pid>`` + fsync +
  ``os.replace``) so a crash mid-snapshot leaves the previous snapshot
  intact.
* ``<prefix>.lease`` — a fencing lease (:class:`FencedLease`): the
  primary refreshes ``{"owner", "control_epoch", "ts"}`` atomically; a
  warm standby polls it, and takes over by replaying the shared journal
  and bumping ``control_epoch`` once the lease goes stale.  Replies
  stamped with a lower epoch than the lease are from a fenced (dead but
  not yet aware) primary and must be rejected.

Records carry *resulting* values rather than deltas, which makes replay
idempotent: a crash between snapshot replace and log truncation
re-applies logged records onto a snapshot that already includes them
and lands on the same state.  Domain replay functions
(``replay_state`` per owner) stay pure over ``(snapshot, records)`` so
property tests can drive them over every record prefix.

Unlike the original dispatcher journal (guarded by the dispatcher's one
big lock), :class:`StateJournal` is internally thread-safe: the
registry appends from its accept loop, sweep loop, and rollout watcher
concurrently.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .logging import get_logger

__all__ = ["StateJournal", "FencedLease"]

logger = get_logger()


class StateJournal:
    """Append-only journal + snapshot pair under one path prefix.

    ``snap_schema`` names the snapshot document schema; a snapshot whose
    schema does not match is discarded on :meth:`load` (the log alone
    rebuilds state from genesis).  ``on_append`` / ``on_snapshot`` are
    optional callbacks (typically ``metrics.counter(...).add``) fired
    after each durable append / compaction so each owner keeps its own
    literal metric names.
    """

    def __init__(self, prefix: str, *, snap_schema: str,
                 on_append: Optional[Callable[[int], Any]] = None,
                 on_snapshot: Optional[Callable[[int], Any]] = None):
        self.prefix = str(prefix)
        self.log_path = self.prefix + ".log"
        self.snap_path = self.prefix + ".snap"
        self.snap_schema = str(snap_schema)
        self._on_append = on_append
        self._on_snapshot = on_snapshot
        d = os.path.dirname(os.path.abspath(self.log_path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.log_path, "ab")
        self.appends_since_snapshot = 0

    # -- write side ------------------------------------------------------
    def append(self, record: Dict[str, Any]) -> None:
        """One fsync'd JSON line; durable before the caller's in-memory
        mutation proceeds (write-ahead ordering)."""
        line = json.dumps(record, sort_keys=True, default=str) + "\n"
        with self._lock:
            self._f.write(line.encode("utf-8"))
            self._f.flush()
            os.fsync(self._f.fileno())
            self.appends_since_snapshot += 1
        if self._on_append is not None:
            self._on_append(1)

    def compact(self, state: Dict[str, Any]) -> None:
        """Atomic-rename snapshot of ``state``, then truncate the log.
        Crash windows: before the replace → old snapshot + full log
        (nothing lost); between replace and truncation → new snapshot +
        old log, whose records re-apply idempotently."""
        doc = {"schema": self.snap_schema, **state}
        tmp = f"{self.snap_path}.tmp.{os.getpid()}"
        with self._lock:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, sort_keys=True, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.snap_path)
            self._f.close()
            self._f = open(self.log_path, "wb")
            os.fsync(self._f.fileno())
            self.appends_since_snapshot = 0
        if self._on_snapshot is not None:
            self._on_snapshot(1)

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    # -- read side -------------------------------------------------------
    def load(self) -> Tuple[Optional[Dict[str, Any]],
                            List[Dict[str, Any]]]:
        """``(snapshot|None, records)`` as found on disk.  A snapshot
        that fails to parse is discarded (the log alone rebuilds state
        from genesis); replay of the log stops at the first torn line."""
        snap: Optional[Dict[str, Any]] = None
        try:
            with open(self.snap_path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema") == self.snap_schema:
                snap = doc
        except (OSError, ValueError):
            snap = None
        records: List[Dict[str, Any]] = []
        try:
            with open(self.log_path, encoding="utf-8") as f:
                for line in f:
                    if not line.endswith("\n"):
                        break               # torn tail: crash mid-append
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            pass
        return snap, records


LEASE_SCHEMA = "dmlc.control.lease/1"


class FencedLease:
    """Atomic fencing lease beside a :class:`StateJournal`.

    The primary stamps ``{"owner", "control_epoch", "ts"}`` into
    ``<prefix>.lease`` with the same ``.tmp.<pid>`` + ``os.replace``
    idiom the snapshot uses; a standby polls :meth:`read` and considers
    the lease expired once ``ts`` is older than ``ttl_s``.  Epochs are
    monotonic: a takeover writes ``control_epoch + 1``, and any primary
    that later wakes up sees a higher epoch than its own on its next
    :meth:`refresh` and must stop serving writes (it has been fenced).
    Wall-clock ``ts`` is fine here — primary and standby share a journal
    prefix, hence a filesystem, hence (in this tree) a clock.
    """

    def __init__(self, path: str, *, ttl_s: float):
        self.path = str(path)
        self.ttl_s = float(ttl_s)

    def read(self) -> Optional[Dict[str, Any]]:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) or doc.get("schema") != LEASE_SCHEMA:
            return None
        return doc

    def refresh(self, owner: str, control_epoch: int) -> bool:
        """Re-stamp the lease.  Returns ``False`` (without writing) when
        the on-disk lease already carries a *higher* epoch — the caller
        has been fenced by a standby takeover and must stand down."""
        cur = self.read()
        if cur is not None and int(cur.get("control_epoch", 0)) > int(control_epoch):
            return False
        doc = {"schema": LEASE_SCHEMA, "owner": str(owner),
               "control_epoch": int(control_epoch), "ts": time.time()}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return True

    def expired(self, now: Optional[float] = None) -> bool:
        doc = self.read()
        if doc is None:
            return True
        return (now if now is not None else time.time()) - float(doc.get("ts", 0.0)) > self.ttl_s

    def current_epoch(self) -> int:
        doc = self.read()
        return int(doc.get("control_epoch", 0)) if doc else 0
