"""Wall-clock timing utilities (reference ``include/dmlc/timer.h``).

``get_time()`` mirrors ``dmlc::GetTime()`` (`timer.h:27`): seconds as float,
monotonic where available.  ``Timer`` adds a simple scope/stopwatch helper used
by throughput instrumentation (reference prints MB/s inline,
`basic_row_iter.h:68-76`).
"""

from __future__ import annotations

import time
from typing import Optional

__all__ = ["get_time", "Timer"]


def get_time() -> float:
    """Seconds from a monotonic clock (reference ``GetTime`` `timer.h:27`)."""
    return time.monotonic()


class Timer:
    """Stopwatch: ``with Timer() as t: ...; t.elapsed``."""

    def __init__(self) -> None:
        self.start: Optional[float] = None
        self.elapsed: float = 0.0

    def __enter__(self) -> "Timer":
        self.start = get_time()
        return self

    def __exit__(self, *exc: object) -> None:
        self.elapsed = get_time() - self.start  # type: ignore[operator]

    def restart(self) -> None:
        self.start = get_time()
        self.elapsed = 0.0

    def lap(self) -> float:
        return get_time() - (self.start if self.start is not None else get_time())
