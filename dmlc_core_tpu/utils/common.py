"""Small shared helpers — capability parity with reference
``include/dmlc/common.h`` and ``include/dmlc/endian.h``.

* :func:`split` — delimiter split skipping empty fields (`common.h:20-37`).
* :func:`hash_combine` — boost-style hash mixing (`common.h:41-46`).
* :func:`byteswap` — endian swap over a bytes-like of fixed-size elements
  (`endian.h:30-40`); numpy does this on arrays, this covers raw buffers.
"""

from __future__ import annotations

from typing import List

__all__ = ["split", "hash_combine", "byteswap"]


def split(s: str, delim: str) -> List[str]:
    """Split mirroring ``dmlc::Split`` (`common.h:20-37`): istream getline
    semantics — interior empties are kept, a trailing delimiter does NOT
    produce an empty last segment, empty input yields []."""
    if s == "":
        return []
    parts = s.split(delim)
    if parts and parts[-1] == "":
        parts.pop()
    return parts


def hash_combine(seed: int, value: int) -> int:
    """Boost ``hash_combine`` mixing (reference `common.h:41-46`)."""
    return (seed ^ (value + 0x9E3779B9 + ((seed << 6) & 0xFFFFFFFF)
                    + (seed >> 2))) & 0xFFFFFFFF


def byteswap(data: bytes, elem_size: int) -> bytes:
    """Swap endianness of each ``elem_size``-byte element
    (reference ``ByteSwap`` `endian.h:30-40`)."""
    if elem_size == 1:
        return bytes(data)
    if len(data) % elem_size:
        raise ValueError(f"buffer of {len(data)} bytes is not a multiple "
                         f"of elem size {elem_size}")
    out = bytearray(len(data))
    for i in range(0, len(data), elem_size):
        out[i:i + elem_size] = data[i:i + elem_size][::-1]
    return bytes(out)
