"""Opt-in runtime lock-order checker for the threaded data/serving plane.

``dmlclint``'s *lock-discipline* rule catches single-class mistakes
statically; what it cannot see is cross-object ordering — the batcher
thread taking ``A`` then ``B`` while a reload thread takes ``B`` then
``A`` deadlocks only under load, and only sometimes.  This module is
the dynamic half of the contract: with ``DMLC_LOCKCHECK=1`` every
``threading.Lock``/``RLock`` *created from package code* is wrapped in
an :class:`InstrumentedLock` that

* maintains a per-thread stack of held locks,
* records a global acquired-before edge graph between lock instances,
* reports a **lock-order inversion** the moment an acquisition creates
  a cycle (``A→B`` recorded while a ``B→…→A`` path exists) — i.e. the
  deadlock is flagged on the orderings alone, without needing the
  unlucky interleaving that would actually hang,
* flags **anomalous hold times** (``DMLC_LOCKCHECK_HOLD_S``, default
  1.0s) — a lock held across a blocking call is the usual prelude to
  an inversion being load-bearing.

Findings feed ``lockcheck.{inversions,long_holds}`` counters plus the
``lockcheck.hold_s`` histogram, and each inversion drops a note into
the flight recorder so a later incident bundle carries the ordering
evidence — all via a daemon flusher thread, never synchronously from
the bookkeeping path (a GC-run ``__del__`` can release an
instrumented lock while this thread holds the metrics registry lock;
emitting right there would re-enter the registry and hang).  Everything is process-local and off unless installed:
importing this module costs nothing at runtime.

Usage::

    from dmlc_core_tpu.utils import lockcheck
    if lockcheck.enabled():        # DMLC_LOCKCHECK=1
        lockcheck.install()
    ...
    print(lockcheck.report())      # {"inversions": [...], ...}

Instance (id-based) edges are deliberate: aggregating by creation site
would merge every ``ConcurrentBlockingQueue``'s lock into one node and
manufacture cycles between unrelated queue instances.  The cost is
that orderings are only learned per-instance — run representative
traffic (the tier-1 suite does) for coverage.

The reporting plane itself (``utils/metrics.py``,
``telemetry/flight.py``, ``telemetry/trace.py``) is exempt from the
shim: its locks are where findings get emitted, and instrumenting
them lets the observer deadlock the observed (releasing a per-metric
lock inside ``MetricsRegistry.snapshot`` — registry lock held — would
observe ``lockcheck.hold_s`` and re-enter the registry).

Caveat: ``threading.Condition()`` *without* a lock argument creates
its ``RLock`` inside ``threading.py``; the factory attributes that
allocation to the ``Condition()`` caller so package conditions are
instrumented while CPython's own internals (``Event``, ``Thread``
bookkeeping) stay raw.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from .metrics import metrics
from .parameter import get_env, parse_lenient_bool

__all__ = ["InstrumentedLock", "enabled", "install", "uninstall",
           "installed", "report", "reset", "flush", "make_lock",
           "make_rlock"]

# real factories, captured before any monkeypatching
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)

#: the planes findings are emitted into stay raw: releasing an
#: instrumented per-metric lock inside ``MetricsRegistry.snapshot``
#: (registry lock held) would observe ``lockcheck.hold_s`` → re-enter
#: the registry lock → self-deadlock.  The observer cannot also be
#: the observed.
_SELF_PLANE = (os.path.join("utils", "metrics.py"),
               os.path.join("telemetry", "flight.py"),
               os.path.join("telemetry", "trace.py"))

# -- global checker state (guarded by _meta) --------------------------------
# _meta is reentrant on purpose: bookkeeping allocates, allocation can
# trigger GC, GC can run a package __del__ that releases an instrumented
# lock — re-entering the bookkeeping while _meta is already held by this
# very thread.  A plain lock would self-deadlock there.
_meta = _REAL_RLOCK()
_graph: Dict[int, Set[int]] = {}        # lock id → ids acquired after it
_names: Dict[int, str] = {}             # lock id → creation site / name
_inversions: List[Dict[str, Any]] = []
_long_holds: List[Dict[str, Any]] = []
_reported_pairs: Set[Tuple[int, int]] = set()
_installed = False
_tls = threading.local()

#: findings queued for metrics/flight emission.  Bookkeeping must NEVER
#: call into the reporting plane synchronously: a GC-run __del__ can
#: release an instrumented lock while *this thread* already holds the
#: (raw, non-reentrant) metrics registry lock mid-``_get`` — observing
#: ``lockcheck.hold_s`` right there re-enters the registry and hangs.
#: deque append/popleft are GIL-atomic; the flusher thread drains.
_pending: "collections.deque[Tuple[str, Dict[str, Any]]]" = \
    collections.deque(maxlen=65536)
_flusher: Optional[threading.Thread] = None


def enabled() -> bool:
    """True when ``DMLC_LOCKCHECK`` opts the process in."""
    return parse_lenient_bool("DMLC_LOCKCHECK") is True


def _held() -> List["_HeldEntry"]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class _HeldEntry:
    __slots__ = ("lock_id", "t0")

    def __init__(self, lock_id: int, t0: float) -> None:
        self.lock_id = lock_id
        self.t0 = t0


def _path_exists(src: int, dst: int) -> bool:
    """BFS over the edge graph; caller holds ``_meta``."""
    if src == dst:
        return True
    seen = {src}
    frontier = [src]
    while frontier:
        nxt = []
        for n in frontier:
            # copy: re-entrant bookkeeping (GC __del__) may grow the set
            for m in tuple(_graph.get(n, ())):
                if m == dst:
                    return True
                if m not in seen:
                    seen.add(m)
                    nxt.append(m)
        frontier = nxt
    return False


def _call_site() -> str:
    """First stack frame outside this module — where acquire() happened."""
    f = sys._getframe(2)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fn = f.f_code.co_filename
    try:
        fn = os.path.relpath(fn, _REPO_ROOT)
    except ValueError:
        pass
    return f"{fn}:{f.f_lineno}"


class InstrumentedLock:
    """Lock/RLock wrapper that feeds the order graph and hold timer.

    Implements the full ``threading`` lock protocol **plus** the
    private ``_release_save``/``_acquire_restore``/``_is_owned`` hooks
    so a wrapped lock can back a ``threading.Condition``.
    """

    __slots__ = ("_raw", "name", "reentrant", "_owner", "_depth", "_hold_s")

    def __init__(self, raw: Any, name: str, reentrant: bool) -> None:
        self._raw = raw
        self.name = name
        self.reentrant = reentrant
        self._owner: Optional[int] = None   # ident, reentrant only
        self._depth = 0
        self._hold_s = float(get_env("DMLC_LOCKCHECK_HOLD_S", 1.0))

    # -- acquisition bookkeeping ----------------------------------------
    #
    # Bookkeeping records findings into checker state and enqueues the
    # metrics/flight emission for the flusher thread — never calling
    # the reporting plane from here (see ``_pending``).  ``_tls.busy``
    # makes the flusher's own lock use invisible to the checker, and
    # the tuple() copies keep a GC-run __del__'s re-entrant bookkeeping
    # from mutating a set/list this frame is iterating.

    def _note_acquired(self) -> None:
        if getattr(_tls, "busy", False):
            return
        held = _held()
        me = id(self)
        if held:
            with _meta:
                for h in tuple(held):
                    if h.lock_id == me:
                        continue
                    edges = _graph.setdefault(h.lock_id, set())
                    if me in edges:
                        continue
                    # new ordering h → me; a me→…→h path means a cycle
                    if _path_exists(me, h.lock_id):
                        pair = (min(h.lock_id, me), max(h.lock_id, me))
                        if pair not in _reported_pairs:
                            _reported_pairs.add(pair)
                            inversion = {
                                "held": _names.get(h.lock_id, "?"),
                                "acquiring": _names.get(me, "?"),
                                "thread": threading.current_thread().name,
                                "site": _call_site(),
                            }
                            _inversions.append(inversion)
                            _pending.append(("inversion", inversion))
                    edges.add(me)
        held.append(_HeldEntry(me, time.monotonic()))

    def _note_released(self) -> None:
        if getattr(_tls, "busy", False):
            return
        held = _held()
        me = id(self)
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock_id == me:
                dt = time.monotonic() - held[i].t0
                del held[i]
                _pending.append(("hold", {"hold_s": dt}))
                if dt > self._hold_s:
                    info = {"lock": self.name, "hold_s": round(dt, 4),
                            "thread": threading.current_thread().name}
                    with _meta:
                        _long_holds.append(info)
                    _pending.append(("long_hold", info))
                return

    # -- lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ident = threading.get_ident()
        if self.reentrant and self._owner == ident:
            self._raw.acquire(blocking, timeout)
            self._depth += 1
            return True
        got = self._raw.acquire(blocking, timeout)
        if got:
            if self.reentrant:
                self._owner = ident
                self._depth = 1
            self._note_acquired()
        return got

    def release(self) -> None:
        if self.reentrant and self._owner == threading.get_ident() \
                and self._depth > 1:
            self._depth -= 1
            self._raw.release()
            return
        if self.reentrant:
            self._owner = None
            self._depth = 0
        self._note_released()
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self.reentrant else "Lock"
        return f"<InstrumentedLock {kind} {self.name}>"

    # -- Condition support ----------------------------------------------

    def _release_save(self) -> Any:
        """Full release for ``Condition.wait`` (drops reentrant depth)."""
        self._note_released()
        if self.reentrant:
            self._owner = None
            depth, self._depth = self._depth, 0
            if hasattr(self._raw, "_release_save"):
                return ("raw", self._raw._release_save())
            for _ in range(depth):
                self._raw.release()
            return ("depth", depth)
        self._raw.release()
        return ("plain", None)

    def _acquire_restore(self, state: Any) -> None:
        kind, payload = state
        if kind == "raw":
            self._raw._acquire_restore(payload)
            self._owner = threading.get_ident()
            self._depth = 1
        elif kind == "depth":
            for _ in range(payload):
                self._raw.acquire()
            self._owner = threading.get_ident()
            self._depth = payload
        else:
            self._raw.acquire()
        # a post-wait reacquire re-enters the held stack but records no
        # ordering edges: the wait already proved other threads take this
        # lock between our hold windows, and counting the reacquire
        # against locks still held across the wait() would be noise
        if not getattr(_tls, "busy", False):
            _held().append(_HeldEntry(id(self), time.monotonic()))

    def _is_owned(self) -> bool:
        if self.reentrant:
            return self._owner == threading.get_ident()
        if hasattr(self._raw, "_is_owned"):
            return self._raw._is_owned()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True


def _register(lock: InstrumentedLock) -> InstrumentedLock:
    with _meta:
        _names[id(lock)] = lock.name
    return lock


def make_lock(name: str) -> InstrumentedLock:
    """Explicitly-named instrumented lock (tests / ad-hoc probes)."""
    return _register(InstrumentedLock(_REAL_LOCK(), name, reentrant=False))


def make_rlock(name: str) -> InstrumentedLock:
    return _register(InstrumentedLock(_REAL_RLOCK(), name, reentrant=True))


def _factory(reentrant: bool):
    def make():
        raw = (_REAL_RLOCK if reentrant else _REAL_LOCK)()
        frame = sys._getframe(1)
        fname = frame.f_code.co_filename
        if os.path.basename(fname) == "threading.py":
            if frame.f_code.co_name != "__init__" or frame.f_back is None:
                return raw          # Event/Thread internals stay raw
            # Condition() with no lock: attribute to Condition()'s caller
            frame = frame.f_back
            fname = frame.f_code.co_filename
        apath = os.path.abspath(fname)
        if not apath.startswith(_PKG_DIR + os.sep) \
                or apath.endswith(_SELF_PLANE):
            return raw              # only package-owned locks are shimmed,
            #                         and never the reporting plane's own
        try:
            rel = os.path.relpath(fname, _REPO_ROOT)
        except ValueError:
            rel = fname
        return _register(InstrumentedLock(
            raw, f"{rel}:{frame.f_lineno}", reentrant))
    return make


def flush() -> None:
    """Drain queued findings into metrics + the flight recorder.

    Runs on the flusher thread (and in tests); safe to call from any
    thread that is not inside the metrics registry.
    """
    drained: List[Tuple[str, Dict[str, Any]]] = []
    while True:
        try:
            drained.append(_pending.popleft())
        except IndexError:
            break
    if not drained:
        return
    _tls.busy = True
    try:
        for kind, info in drained:
            if kind == "hold":
                metrics.histogram("lockcheck.hold_s").observe(
                    info["hold_s"])
            elif kind == "long_hold":
                metrics.counter("lockcheck.long_holds").add(1)
            elif kind == "inversion":
                metrics.counter("lockcheck.inversions").add(1)
                try:
                    from ..telemetry.flight import note
                    note("lockcheck.inversion", **info)
                except Exception:  # noqa: BLE001 — diagnostics only
                    pass
    finally:
        _tls.busy = False


def _flusher_loop() -> None:
    while _installed:
        flush()
        time.sleep(0.5)


def install() -> None:
    """Shim ``threading.Lock``/``RLock`` creation for package modules."""
    global _installed, _flusher
    if _installed:
        return
    threading.Lock = _factory(reentrant=False)    # type: ignore[misc]
    threading.RLock = _factory(reentrant=True)    # type: ignore[misc]
    _installed = True
    if _flusher is None or not _flusher.is_alive():
        _flusher = threading.Thread(target=_flusher_loop, daemon=True,
                                    name="lockcheck-flusher")
        _flusher.start()


def uninstall() -> None:
    """Restore the real factories (existing wrapped locks keep working)."""
    global _installed
    threading.Lock = _REAL_LOCK                   # type: ignore[misc]
    threading.RLock = _REAL_RLOCK                 # type: ignore[misc]
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Drop accumulated graph/findings (tests)."""
    with _meta:
        _graph.clear()
        _names.clear()
        _inversions.clear()
        _long_holds.clear()
        _reported_pairs.clear()
        _pending.clear()


def report() -> Dict[str, Any]:
    with _meta:
        return {
            "installed": _installed,
            "locks": len(_names),
            "edges": sum(len(v) for v in _graph.values()),
            "inversions": list(_inversions),
            "long_holds": list(_long_holds),
        }
