"""Metrics & tracing subsystem — the structured upgrade over the
reference's ad-hoc instrumentation (SURVEY §5).

The reference's observability is wall-clock ``GetTime()`` (`timer.h:27`)
plus periodic MB/s prints in ingest loops (`basic_row_iter.h:68-76`,
`disk_row_iter.h:120-126`) and a tracker job-duration log
(`tracker.py:317-320`). This module keeps those habits but makes them
first-class and queryable:

* :class:`Counter` / :class:`Gauge` — monotonic / point-in-time values.
* :class:`Histogram` — value distribution with quantile estimation
  (p50/p95/p99 request latency is the serving subsystem's SLO surface;
  exact up to a sample cap, reservoir-sampled beyond it).
* :class:`ThroughputMeter` — bytes-or-records rate with total + windowed
  rate (what the MB/s prints computed inline).
* :class:`StageTimer` — accumulated wall time per pipeline stage, usable
  as a context manager or decorator; exposes count/total/mean.
* :class:`MetricsRegistry` — process-global named registry with
  ``snapshot()`` (one dict, JSON-serializable) and ``report()`` logging.
* :func:`trace_span` — context manager emitting a ``jax.profiler``
  TraceAnnotation when JAX is importable (shows up on the TPU trace
  timeline), and a no-op otherwise; the idiomatic replacement for the
  reference's printf timing.
* :func:`profile_trace` — wrap a block in ``jax.profiler``
  start_trace/stop_trace for offline TensorBoard inspection.
"""

from __future__ import annotations

import contextlib
import math
import random
import sys
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

from .logging import log_info

__all__ = [
    "Counter", "Gauge", "Histogram", "ThroughputMeter", "StageTimer",
    "MetricsRegistry", "metrics", "trace_span", "profile_trace",
]


class Counter:
    """Monotonic counter (thread-safe)."""

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"type": "counter", "value": self._v}

    def state(self) -> Dict[str, Any]:
        """Serialized mergeable state (same as snapshot for counters)."""
        return self.snapshot()

    @classmethod
    def merge(cls, states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        return {"type": "counter",
                "value": sum(int(s.get("value", 0)) for s in states)}


class Gauge:
    """Last-set value."""

    def __init__(self) -> None:
        self._v: float = 0.0

    def set(self, v: float) -> None:
        self._v = v

    @property
    def value(self) -> float:
        return self._v

    def snapshot(self) -> Dict[str, Any]:
        return {"type": "gauge", "value": self._v}

    def state(self) -> Dict[str, Any]:
        return self.snapshot()

    @classmethod
    def merge(cls, states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        """Fleet view of a gauge is the worst (max) rank — health-style
        gauges encode severity as magnitude (0 ok / 1 degraded / ...)."""
        vals = [float(s.get("value", 0.0)) for s in states]
        return {"type": "gauge", "value": max(vals) if vals else 0.0}


#: exemplar slots per histogram — one per value region (well below
#: half the mean, below the mean, up to 2x the mean, the tail beyond)
_EXEMPLAR_SLOTS = 4


def _active_trace_hex() -> Optional[str]:
    """Hex trace id of the ambient trace context, or None.

    Resolved through ``sys.modules`` so this module never imports the
    telemetry package (which imports it back): if tracing was never
    imported there are no traces to reference, and the probe costs one
    dict lookup.
    """
    tr = sys.modules.get("dmlc_core_tpu.telemetry.trace")
    if tr is None:
        return None
    try:
        return tr.current_trace_id()
    except Exception:
        return None


class Histogram:
    """Value distribution with quantile estimation (thread-safe).

    Exact while the stream fits in ``max_samples``; past that, reservoir
    sampling keeps a uniform sample of everything seen so far, so
    quantiles stay unbiased over unbounded streams at O(1) memory while
    count/sum/min/max remain exact.  The reservoir RNG is seeded, so a
    replayed stream reports identical quantiles.

    When an observation happens inside an active trace context, the
    (value, trace_id, ts) triple is retained as an *exemplar* in one of
    :data:`_EXEMPLAR_SLOTS` slots bucketed by value region relative to
    the running mean — so the tail slot always references a concrete
    slow request.  Exemplars ride :meth:`snapshot` (key absent when none
    exist) and render in the OpenMetrics exposition format.
    """

    def __init__(self, max_samples: int = 8192, seed: int = 0) -> None:
        if max_samples <= 0:
            raise ValueError("max_samples must be > 0")
        self._cap = int(max_samples)
        self._samples: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._rng = random.Random(seed)
        self._exemplars: List[Any] = [None] * _EXEMPLAR_SLOTS
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        tid = _active_trace_hex()
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if len(self._samples) < self._cap:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self._count)
                if j < self._cap:
                    self._samples[j] = v
            if tid is not None:
                mean = self._sum / self._count
                slot = (0 if v <= 0.5 * mean else
                        1 if v <= mean else
                        2 if v <= 2.0 * mean else 3)
                self._exemplars[slot] = (v, tid, time.time())

    @contextlib.contextmanager
    def time(self, clock: Callable[[], float] = time.monotonic
             ) -> Iterator[None]:
        """Observe the wall time of a block (seconds)."""
        t0 = clock()
        try:
            yield
        finally:
            self.observe(clock() - t0)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    @property
    def min(self) -> float:
        return self._min if self._count else 0.0

    @property
    def max(self) -> float:
        return self._max if self._count else 0.0

    def quantile(self, q: float) -> float:
        return self.quantiles([q])[0]

    @staticmethod
    def _interp(sorted_samples: List[float], qs: Sequence[float]
                ) -> List[float]:
        """Linear interpolation between closest ranks (numpy's default)."""
        for q in qs:
            if not 0.0 <= q <= 1.0:
                raise ValueError(f"quantile must be in [0, 1], got {q}")
        s = sorted_samples
        if not s:
            return [0.0 for _ in qs]
        out = []
        for q in qs:
            pos = q * (len(s) - 1)
            lo = int(math.floor(pos))
            hi = min(lo + 1, len(s) - 1)
            out.append(s[lo] + (pos - lo) * (s[hi] - s[lo]))
        return out

    def quantiles(self, qs: Sequence[float]) -> List[float]:
        """Quantiles over the (possibly sampled) observation set."""
        with self._lock:
            s = sorted(self._samples)
        return self._interp(s, qs)

    def snapshot(self) -> Dict[str, Any]:
        # One lock acquisition for the whole view: quantiles, count, and
        # moments must describe the same instant or a concurrent observe()
        # tears the snapshot (count ahead of sum, quantile behind max).
        with self._lock:
            count, sum_ = self._count, self._sum
            mn = self._min if count else 0.0
            mx = self._max if count else 0.0
            s = sorted(self._samples)
            ex = [{"value": val, "trace_id": t, "ts": ts}
                  for (val, t, ts) in
                  (e for e in self._exemplars if e is not None)]
        p50, p95, p99 = self._interp(s, [0.5, 0.95, 0.99])
        snap = {"type": "histogram", "count": count,
                "mean": sum_ / count if count else 0.0, "min": mn, "max": mx,
                "p50": p50, "p95": p95, "p99": p99}
        if ex:
            # additive key: absent when no traced observation happened,
            # so snapshot consumers that never see traces are unchanged
            snap["exemplars"] = ex
        return snap

    def state(self) -> Dict[str, Any]:
        """Serialized reservoir state — exact moments + the sample set —
        consistent under one lock.  This is what ranks ship to the tracker;
        :meth:`merge` reconstructs fleet quantiles from a list of these."""
        with self._lock:
            count = self._count
            return {"type": "histogram", "count": count, "sum": self._sum,
                    "min": self._min if count else 0.0,
                    "max": self._max if count else 0.0,
                    "samples": list(self._samples)}

    @classmethod
    def merge(cls, states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        """Merge serialized states into one snapshot-form dict.

        Moments (count/sum/min/max) merge exactly.  Quantiles come from
        the union of reservoirs with each sample weighted by how many
        observations it stands for (``count_i / len(samples_i)``), so a
        rank that saw 10x the traffic pulls the fleet quantile 10x harder.
        Exact when no reservoir ever overflowed (weights all 1).
        """
        count = 0
        sum_ = 0.0
        mn, mx = math.inf, -math.inf
        weighted: List[Any] = []   # (value, weight) pairs
        for s in states:
            c = int(s.get("count", 0))
            if c <= 0:
                continue
            count += c
            sum_ += float(s.get("sum", 0.0))
            mn = min(mn, float(s.get("min", math.inf)))
            mx = max(mx, float(s.get("max", -math.inf)))
            samples = s.get("samples") or []
            if samples:
                w = c / len(samples)
                weighted.extend((float(v), w) for v in samples)
        if not count:
            return {"type": "histogram", "count": 0, "mean": 0.0,
                    "min": 0.0, "max": 0.0, "p50": 0.0, "p95": 0.0,
                    "p99": 0.0}
        weighted.sort(key=lambda vw: vw[0])
        p50, p95, p99 = cls._weighted_quantiles(weighted, [0.5, 0.95, 0.99])
        return {"type": "histogram", "count": count, "mean": sum_ / count,
                "min": mn, "max": mx, "p50": p50, "p95": p95, "p99": p99}

    @staticmethod
    def _weighted_quantiles(sorted_vw: List[Any], qs: Sequence[float]
                            ) -> List[float]:
        """Weighted quantiles by the midpoint rule: sample i sits at
        cumulative position ``cum_i - w_i/2``; interpolate between the
        bracketing samples.  Reduces to :meth:`_interp` for equal weights."""
        total_w = sum(w for _, w in sorted_vw)
        if total_w <= 0:
            return [0.0 for _ in qs]
        pos = []
        cum = 0.0
        for _, w in sorted_vw:
            pos.append(cum + w / 2.0)
            cum += w
        out = []
        for q in qs:
            target = q * total_w
            if target <= pos[0]:
                out.append(sorted_vw[0][0])
                continue
            if target >= pos[-1]:
                out.append(sorted_vw[-1][0])
                continue
            # binary search for the bracketing pair
            lo, hi = 0, len(pos) - 1
            while hi - lo > 1:
                mid = (lo + hi) // 2
                if pos[mid] <= target:
                    lo = mid
                else:
                    hi = mid
            v0, v1 = sorted_vw[lo][0], sorted_vw[hi][0]
            span = pos[hi] - pos[lo]
            frac = (target - pos[lo]) / span if span > 0 else 0.0
            out.append(v0 + frac * (v1 - v0))
        return out


class ThroughputMeter:
    """Rate meter: total units + overall and windowed rates.

    The structured form of the reference's inline MB/s computation
    (`basic_row_iter.h:70-75`): ``add(n)`` per batch, ``rate()`` anywhere.
    """

    def __init__(self, window_sec: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._start = clock()
        self._total = 0
        self._win_start = self._start
        self._win_total = 0
        self._win_rate = 0.0
        self._win_closed = False
        self._window = window_sec
        self._lock = threading.Lock()

    def add(self, n: int) -> None:
        with self._lock:
            self._total += n
            self._win_total += n
            now = self._clock()
            if now - self._win_start >= self._window:
                self._win_rate = self._win_total / (now - self._win_start)
                self._win_closed = True
                self._win_start = now
                self._win_total = 0

    @property
    def total(self) -> int:
        return self._total

    def _rate_locked(self, now: float) -> float:
        dt = now - self._start
        return self._total / dt if dt > 0 else 0.0

    def _windowed_locked(self, now: float) -> float:
        elapsed = now - self._win_start
        if elapsed >= self._window:
            # window overdue: rate over the open (possibly stalled) span
            return self._win_total / elapsed
        if self._win_closed:
            return self._win_rate
        return self._rate_locked(now)   # before the first window closes

    def rate(self) -> float:
        """Overall units/sec since construction."""
        with self._lock:
            return self._rate_locked(self._clock())

    def windowed_rate(self) -> float:
        """Units/sec over the current/most recent window. A stalled stream
        (no ``add`` calls) decays toward 0 as the open window ages — it must
        NOT keep reporting the last healthy rate."""
        with self._lock:
            return self._windowed_locked(self._clock())

    def snapshot(self) -> Dict[str, Any]:
        # total and both rates read at one instant under one lock — a
        # concurrent add() between them would report rate ahead of total
        with self._lock:
            now = self._clock()
            return {"type": "throughput", "total": self._total,
                    "rate": self._rate_locked(now),
                    "windowed_rate": self._windowed_locked(now)}

    def state(self) -> Dict[str, Any]:
        return self.snapshot()

    @classmethod
    def merge(cls, states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        """Totals and rates sum across ranks (parallel streams)."""
        return {"type": "throughput",
                "total": sum(int(s.get("total", 0)) for s in states),
                "rate": sum(float(s.get("rate", 0.0)) for s in states),
                "windowed_rate": sum(float(s.get("windowed_rate", 0.0))
                                     for s in states)}


class StageTimer:
    """Accumulated wall time for one pipeline stage.

    Use as context manager::

        with metrics.stage("parse").time():
            ...

    or decorate a function with the timer itself
    (``@metrics.stage("parse")``). Reports count / total / mean seconds.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._count = 0
        self._total = 0.0
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            with self._lock:
                self._count += 1
                self._total += dt

    def __call__(self, fn: Callable) -> Callable:
        def wrapped(*a, **kw):
            with self.time():
                return fn(*a, **kw)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    @property
    def count(self) -> int:
        return self._count

    @property
    def total_sec(self) -> float:
        return self._total

    @property
    def mean_sec(self) -> float:
        return self._total / self._count if self._count else 0.0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:   # count and total from the same instant
            count, total = self._count, self._total
        return {"type": "stage", "count": count, "total_sec": total,
                "mean_sec": total / count if count else 0.0}

    def state(self) -> Dict[str, Any]:
        return self.snapshot()

    @classmethod
    def merge(cls, states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        count = sum(int(s.get("count", 0)) for s in states)
        total = sum(float(s.get("total_sec", 0.0)) for s in states)
        return {"type": "stage", "count": count, "total_sec": total,
                "mean_sec": total / count if count else 0.0}


class MetricsRegistry:
    """Named metrics with one-call snapshot/report.

    Hierarchical names by convention (``ingest.bytes``, ``device.batches``).
    """

    def __init__(self) -> None:
        self._m: Dict[str, Any] = {}
        self._lock = threading.Lock()
        #: bumped by reset(); hot paths that cache metric handles compare
        #: this (one int read, no lock) and re-fetch when it changes
        self.generation = 0

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._m.get(name)
            if m is None:
                m = cls(**kw)
                self._m[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, max_samples: int = 8192) -> Histogram:
        return self._get(name, Histogram, max_samples=max_samples)

    def throughput(self, name: str, window_sec: float = 5.0) -> ThroughputMeter:
        return self._get(name, ThroughputMeter, window_sec=window_sec)

    def stage(self, name: str) -> StageTimer:
        return self._get(name, StageTimer)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: v.snapshot() for k, v in sorted(self._m.items())}

    def state(self) -> Dict[str, Dict[str, Any]]:
        """Serialized mergeable view of every metric (histograms carry
        their reservoir).  This is the payload workers push to the
        tracker; ``telemetry.aggregate`` merges a set of them."""
        with self._lock:
            items = sorted(self._m.items())
        return {k: (v.state() if hasattr(v, "state") else v.snapshot())
                for k, v in items}

    def report(self) -> None:
        for name, snap in self.snapshot().items():
            log_info("metric %s: %s", name,
                     " ".join(f"{k}={v:.3f}" if isinstance(v, float)
                              else f"{k}={v}" for k, v in snap.items()
                              if k != "type"))

    def reset(self) -> None:
        with self._lock:
            self._m.clear()
            self.generation += 1


#: process-global registry (modules grab sub-metrics by name)
metrics = MetricsRegistry()


# jax.profiler resolved once at first trace_span() use; False caches the
# negative case so a JAX-less process pays the failed import exactly once
_profiler_mod: Any = None


def _resolve_profiler() -> Any:
    global _profiler_mod
    if _profiler_mod is None:
        try:
            import jax.profiler as _prof
            _profiler_mod = _prof
        except Exception:
            _profiler_mod = False
    return _profiler_mod or None


@contextlib.contextmanager
def trace_span(name: str) -> Iterator[None]:
    """Annotate a host-side span on the jax.profiler timeline; no-op when
    JAX is unavailable. The idiomatic upgrade of printf timing (SURVEY §5)."""
    ann = None
    prof = _resolve_profiler()
    if prof is not None:
        try:
            ann = prof.TraceAnnotation(name)
        except Exception:
            pass
    if ann is None:
        yield
        return
    with ann:
        yield


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace (view in TensorBoard / Perfetto)."""
    import jax.profiler as _prof
    _prof.start_trace(log_dir)
    try:
        yield
    finally:
        _prof.stop_trace()
