"""Checkpoint/resume substrate — the policy layer the reference leaves to
downstream, built on the substrate it ships (SURVEY §5 checkpoint/resume):
``Serializable`` Load/Save (`io.h:112-126`), the STL/struct serializer
(`serializer.h`), binary RowBlock Save/Load (`row_block.h:181-205`) and
parameter JSON save/load (`parameter.h:185-197`).

TPU-native expression:

* :func:`save_pytree` / :func:`load_pytree` — stream-serialize a nested
  dict/list/tuple of arrays (jax or numpy; jax arrays land as numpy and are
  re-``device_put`` by the caller with whatever sharding the restore mesh
  uses — checkpoints are **sharding-agnostic**, the same way reference
  serialization is endian-portable, `serializer.h` ``DMLC_IO_NO_ENDIAN_SWAP``).
* :class:`Serializable` — the duck-typed Save/Load protocol.
* :class:`CheckpointManager` — versioned on-disk checkpoints with atomic
  publish (write to temp + rename), a JSON manifest, latest/step restore and
  bounded retention. Works over any URI the filesystem layer can write
  (local, s3, hdfs...) with atomicity guaranteed on ``file://``.

Step/epoch position of the *data* pipeline is part of the saved state:
``DeviceLoader`` counts consumed batches, and :func:`fast_forward` replays
a restored loader to the recorded position (the ingest analog of the
reference's resumable cache files, `cached_input_split.h`).
"""

from __future__ import annotations

import os
import shutil
import struct
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .json import json_dumps, json_loads
from .logging import DMLCError, check, log_info

__all__ = [
    "Serializable", "save_pytree", "load_pytree", "CheckpointManager",
    "fast_forward", "load_for_inference",
    "flatten_tree", "unflatten_like", "load_pytree_leaves",
]

_MAGIC = b"DMLCKPT1"


class Serializable:
    """Save/Load protocol (reference ``Serializable`` `io.h:112-126`)."""

    def save(self, stream) -> None:
        raise NotImplementedError

    def load(self, stream) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# pytree <-> stream
# ---------------------------------------------------------------------------

def _to_numpy(x):
    """jax.Array (possibly sharded) → host numpy; numpy passes through.
    Rejects object dtype at SAVE time — its raw bytes are pointers and the
    checkpoint would only fail at restore, after the crash it was meant to
    survive."""
    if isinstance(x, np.ndarray):
        arr = x
    elif hasattr(x, "__array__"):    # jax.Array and friends
        arr = np.asarray(x)
    else:
        return None
    if arr.dtype.hasobject:
        raise DMLCError(
            f"cannot checkpoint object-dtype array (dtype {arr.dtype}); "
            f"convert to a numeric/bytes dtype first")
    return arr


def _write_blob(stream, b: bytes) -> None:
    stream.write(struct.pack("<Q", len(b)))
    stream.write(b)


def _read_exact(stream, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = stream.read(n - len(out))
        if not chunk:
            raise DMLCError("checkpoint stream truncated")
        out += chunk
    return out


def _read_blob(stream) -> bytes:
    (n,) = struct.unpack("<Q", _read_exact(stream, 8))
    return _read_exact(stream, n)


def save_pytree(stream, tree: Any) -> None:
    """Serialize a pytree of arrays/scalars. Layout: magic, JSON treedef
    (structure with leaf placeholders), then each array leaf as
    (dtype, shape, raw bytes)."""
    leaves: List[np.ndarray] = []

    def strip(node):
        arr = _to_numpy(node)
        if arr is not None:
            leaves.append(arr)
            return {"__leaf__": len(leaves) - 1}
        if isinstance(node, dict):
            check(all(isinstance(k, str) for k in node),
                  "checkpoint dict keys must be str")
            check("__leaf__" not in node and "__tuple__" not in node,
                  "reserved key in checkpoint tree")
            return {k: strip(v) for k, v in node.items()}
        if isinstance(node, tuple):
            return {"__tuple__": [strip(v) for v in node]}
        if isinstance(node, list):
            return [strip(v) for v in node]
        if node is None or isinstance(node, (bool, int, float, str)):
            return node
        raise DMLCError(f"cannot checkpoint {type(node).__name__}")

    treedef = strip(tree)
    stream.write(_MAGIC)
    _write_blob(stream, json_dumps(treedef).encode())
    stream.write(struct.pack("<I", len(leaves)))
    for arr in leaves:
        # record the shape BEFORE ascontiguousarray: its contract is
        # "at least 1-d", so a 0-d leaf (e.g. an FM's w0 bias) would be
        # persisted as (1,) and no longer match the model's avals
        shape = arr.shape
        arr = np.ascontiguousarray(arr)
        _write_blob(stream, str(arr.dtype).encode())
        stream.write(struct.pack("<I", len(shape)))
        for d in shape:
            stream.write(struct.pack("<Q", d))
        _write_blob(stream, arr.tobytes())


def load_pytree(stream, template: Any = None) -> Any:
    """Deserialize a pytree. With ``template``, container *types* are taken
    from it (NamedTuples — e.g. optax optimizer states — and custom dicts
    restore as their original classes; a plain load can only produce
    dict/list/tuple)."""
    magic = _read_exact(stream, len(_MAGIC))
    check(magic == _MAGIC, f"not a dmlc checkpoint (magic {magic!r})")
    treedef = json_loads(_read_blob(stream).decode())
    (nleaves,) = struct.unpack("<I", _read_exact(stream, 4))
    leaves = []
    for _ in range(nleaves):
        dtype = np.dtype(_read_blob(stream).decode())
        (ndim,) = struct.unpack("<I", _read_exact(stream, 4))
        shape = tuple(struct.unpack("<Q", _read_exact(stream, 8))[0]
                      for _ in range(ndim))
        raw = _read_blob(stream)
        leaves.append(np.frombuffer(raw, dtype=dtype).reshape(shape).copy())

    def rebuild(node):
        if isinstance(node, dict):
            if "__leaf__" in node:
                return leaves[node["__leaf__"]]
            if "__tuple__" in node:
                return tuple(rebuild(v) for v in node["__tuple__"])
            return {k: rebuild(v) for k, v in node.items()}
        if isinstance(node, list):
            return [rebuild(v) for v in node]
        return node

    def rebuild_like(tmpl, node):
        if isinstance(node, dict) and "__leaf__" in node:
            leaf = leaves[node["__leaf__"]]
            # checkpoints written before the 0-d shape fix hold scalars
            # as (1,); heal single-element leaves to the template's shape
            # so old files keep restoring (larger leaves must still match)
            tshape = getattr(tmpl, "shape", None)
            if (tshape is not None and leaf.size == 1
                    and int(np.prod(tshape)) == 1
                    and tuple(tshape) != leaf.shape):
                leaf = leaf.reshape(tuple(tshape))
            return leaf
        if isinstance(node, dict) and "__tuple__" in node:
            children = node["__tuple__"]
            check(isinstance(tmpl, tuple) and len(tmpl) == len(children),
                  f"template mismatch: expected {len(children)}-tuple, "
                  f"got {type(tmpl).__name__}")
            vals = [rebuild_like(t, c) for t, c in zip(tmpl, children)]
            if hasattr(tmpl, "_fields"):        # NamedTuple: keep the type
                return type(tmpl)(*vals)
            return tuple(vals)
        if isinstance(node, dict):
            check(isinstance(tmpl, dict),
                  f"template mismatch: expected dict, got "
                  f"{type(tmpl).__name__}")
            out = {k: rebuild_like(tmpl[k], v) if k in tmpl else rebuild(v)
                   for k, v in node.items()}
            return type(tmpl)(out) if type(tmpl) is not dict else out
        if isinstance(node, list):
            if isinstance(tmpl, list):
                check(len(tmpl) == len(node),
                      f"template mismatch: list of {len(tmpl)} vs "
                      f"checkpointed {len(node)}")
                return [rebuild_like(ti, v) for ti, v in zip(tmpl, node)]
            return [rebuild(v) for v in node]
        return node

    if template is None:
        return rebuild(treedef)
    return rebuild_like(template, treedef)


# ---------------------------------------------------------------------------
# leaf-path addressing + partial restore
# ---------------------------------------------------------------------------
# A leaf's PATH is its position in the tree with dict keys and list/tuple
# indices joined by "/" ("params/v", "opt_state/0/mu").  The convention is
# shared with parallel/reshard.py — it is how the elastic resharder names
# shards on the wire and how the checkpoint fallback asks for exactly the
# leaves no survivor holds, without materializing the rest of the file.
# Two leaves collide only if a dict key itself contains "/" AND shadows a
# nested path ({"a/b": x} vs {"a": {"b": y}}) — flatten_tree rejects the
# duplicate loudly rather than guessing.

def _join(path: str, key) -> str:
    return f"{path}/{key}" if path else str(key)


def flatten_tree(tree: Any) -> Dict[str, np.ndarray]:
    """Flatten a pytree's ARRAY leaves to ``{path: np.ndarray}``.

    Non-array structure (None/bool/int/float/str) is skipped — it travels
    with the template on restore, exactly as :func:`load_pytree` keeps it
    in the treedef.  Leaf detection matches :func:`save_pytree`."""
    out: Dict[str, np.ndarray] = {}

    def walk(node, path: str) -> None:
        arr = _to_numpy(node)
        if arr is not None:
            check(path not in out, f"duplicate leaf path {path!r}")
            out[path] = arr
            return
        if isinstance(node, dict):
            check(all(isinstance(k, str) for k in node),
                  "tree dict keys must be str")
            for k, v in node.items():
                walk(v, _join(path, k))
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, _join(path, i))
        elif node is None or isinstance(node, (bool, int, float, str)):
            return
        else:
            raise DMLCError(f"cannot flatten {type(node).__name__}")

    walk(tree, "")
    return out


def unflatten_like(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild a pytree shaped like ``template`` from a :func:`flatten_tree`
    mapping.  Container types come from the template (NamedTuples — optax
    states — survive); non-array structure passes through from the
    template; a template array leaf missing from ``flat`` raises."""

    def build(node, path: str):
        arr = _to_numpy(node)
        if arr is not None:
            if path not in flat:
                raise DMLCError(f"unflatten_like: missing leaf {path!r}")
            return flat[path]
        if isinstance(node, dict):
            out = {k: build(v, _join(path, k)) for k, v in node.items()}
            return out if type(node) is dict else type(node)(out)
        if isinstance(node, tuple):
            vals = [build(v, _join(path, i)) for i, v in enumerate(node)]
            if hasattr(node, "_fields"):        # NamedTuple: keep the type
                return type(node)(*vals)
            return tuple(vals)
        if isinstance(node, list):
            return [build(v, _join(path, i)) for i, v in enumerate(node)]
        return node

    return build(template, "")


def _treedef_paths(treedef: Any) -> Dict[int, str]:
    """leaf index → path for a serialized treedef (the JSON structure
    :func:`save_pytree` writes, with ``__leaf__``/``__tuple__`` markers)."""
    out: Dict[int, str] = {}

    def walk(node, path: str) -> None:
        if isinstance(node, dict):
            if "__leaf__" in node:
                out[int(node["__leaf__"])] = path
                return
            if "__tuple__" in node:
                for i, v in enumerate(node["__tuple__"]):
                    walk(v, _join(path, i))
                return
            for k, v in node.items():
                walk(v, _join(path, k))
        elif isinstance(node, list):
            for i, v in enumerate(node):
                walk(v, _join(path, i))

    walk(treedef, "")
    return out


def _skip_bytes(stream, n: int) -> None:
    """Advance past n payload bytes: seek when the stream supports it
    (local files — the whole point of leaf-granular restore), bounded
    read-and-discard otherwise (remote object streams)."""
    try:
        stream.seek(n, 1)
        return
    except (AttributeError, OSError, ValueError):
        pass
    while n > 0:
        chunk = stream.read(min(n, 1 << 20))
        if not chunk:
            raise DMLCError("checkpoint stream truncated")
        n -= len(chunk)


def load_pytree_leaves(stream, paths) -> Dict[str, np.ndarray]:
    """Restore only the named leaves from a :func:`save_pytree` stream.

    Returns ``{path: array}`` for every requested path present in the
    file (absent paths are simply not in the result — the caller decides
    whether that is an error).  Unwanted leaf payloads are seeked over,
    so restoring 2 of 200 leaves costs 2 leaves of I/O plus headers —
    the property the elastic resharder's last-resort path depends on."""
    magic = _read_exact(stream, len(_MAGIC))
    check(magic == _MAGIC, f"not a dmlc checkpoint (magic {magic!r})")
    treedef = json_loads(_read_blob(stream).decode())
    idx2path = _treedef_paths(treedef)
    (nleaves,) = struct.unpack("<I", _read_exact(stream, 4))
    want = set(paths)
    out: Dict[str, np.ndarray] = {}
    for i in range(nleaves):
        dtype = np.dtype(_read_blob(stream).decode())
        (ndim,) = struct.unpack("<I", _read_exact(stream, 4))
        shape = tuple(struct.unpack("<Q", _read_exact(stream, 8))[0]
                      for _ in range(ndim))
        (nbytes,) = struct.unpack("<Q", _read_exact(stream, 8))
        path = idx2path.get(i)
        if path in want:
            raw = _read_exact(stream, nbytes)
            out[path] = np.frombuffer(raw, dtype=dtype).reshape(shape).copy()
            if len(out) == len(want):       # all found: skip the tail
                break
        else:
            _skip_bytes(stream, nbytes)
    return out


# ---------------------------------------------------------------------------
# checkpoint manager
# ---------------------------------------------------------------------------

class _LocalStore:
    """POSIX directory backend: temp file + fsync + rename = atomic publish."""

    def __init__(self, directory: str) -> None:
        self.base = directory
        os.makedirs(directory, exist_ok=True)

    def url(self, name: str) -> str:
        return os.path.join(self.base, name)

    def names(self) -> List[str]:
        return os.listdir(self.base)

    def read_bytes(self, name: str) -> Optional[bytes]:
        try:
            with open(self.url(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def open_read(self, name: str):
        try:
            return open(self.url(name), "rb")
        except FileNotFoundError as e:
            raise DMLCError(f"checkpoint object missing: {self.url(name)}"
                            ) from e

    def write_stream(self, name: str, write_fn) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.base, prefix=f".{name}-")
        try:
            with os.fdopen(fd, "wb") as f:
                write_fn(f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.url(name))       # atomic publish
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def delete(self, name: str) -> None:
        try:
            os.unlink(self.url(name))
        except OSError:
            pass


class _RemoteStore:
    """Object-store backend over the filesystem layer (s3://, hdfs://, …).

    Atomicity comes from the store itself: a PUT (or the multipart
    complete) publishes the whole object at close or not at all, so no
    temp+rename dance is needed (reference gets the same property from
    `s3_filesys.cc` CompleteMultipartUpload)."""

    def __init__(self, base_uri: str) -> None:
        self.base = base_uri.rstrip("/")

    def url(self, name: str) -> str:
        return f"{self.base}/{name}"

    def _fs(self):
        from ..io.filesys import get_filesystem
        from ..io.uri import URI
        return get_filesystem(URI(self.base)), URI

    @staticmethod
    def _is_missing(e: DMLCError) -> bool:
        """'object not found' vs transient backend error.  Only a definite
        not-found may be treated as an empty slot — a 500/timeout must
        propagate, otherwise one S3 blip during save() would rebuild the
        manifest as empty and orphan every prior checkpoint."""
        msg = str(e)
        return "404" in msg or "no such" in msg.lower()

    def names(self) -> List[str]:
        fs, URI = self._fs()
        try:
            infos = fs.list_directory(URI(self.base))
        except DMLCError as e:
            if self._is_missing(e):
                return []           # prefix not created yet: empty store
            raise
        return [i.path.rstrip("/").rsplit("/", 1)[-1] for i in infos]

    def read_bytes(self, name: str) -> Optional[bytes]:
        fs, URI = self._fs()
        uri = URI(self.url(name))
        try:
            fs.get_path_info(uri)
        except DMLCError as e:
            if self._is_missing(e):
                return None
            raise
        with fs.open(uri, "r") as f:
            return f.read()

    def open_read(self, name: str):
        fs, URI = self._fs()
        return fs.open(URI(self.url(name)), "r")

    def write_stream(self, name: str, write_fn) -> None:
        """Atomic publish on an object store: stores whose PUT/multipart-
        complete lands whole-object-or-nothing at close write the final
        name directly; a mid-write failure skips close so nothing is
        published (plus best-effort abort).  Stores with rename (WebHDFS)
        write a temp name and rename, since their appends are visible
        immediately."""
        fs, URI = self._fs()
        rename = getattr(fs, "rename", None)
        target = self.url(name)
        from uuid import uuid4
        wire = (f"{target}.tmp-{uuid4().hex[:8]}" if rename else target)
        f = fs.open(URI(wire), "w")
        try:
            write_fn(f)
        except BaseException:
            abort = getattr(f, "abort", None)
            if abort is not None:
                abort()             # no close → nothing published
            if rename:
                try:
                    f.close()
                    fs.delete(URI(wire))
                except DMLCError:
                    pass
            raise
        f.close()
        if rename:
            rename(URI(wire), URI(target))

    def delete(self, name: str) -> None:
        fs, URI = self._fs()
        try:
            fs.delete(URI(self.url(name)))
        except DMLCError as e:
            # backend without delete: retention leaves orphans (logged) —
            # the manifest no longer references them so restores are safe
            log_info("checkpoint: could not prune %s (%s)", name, e)


class CheckpointManager:
    """Versioned checkpoints with atomic publish and bounded retention.

    ``directory`` may be a local path or any URI the filesystem layer can
    write (``s3://bucket/run1``, ``hdfs://nn:9870/ckpt``, …) — distributed
    jobs checkpoint straight to the object store, the TPU-native analog of
    the reference pushing rabit checkpoints over hdfs.

    Layout::

        <dir>/ckpt-<step>.bin     one pytree per step
        <dir>/MANIFEST.json       {"latest": step, "steps": [...], "meta": {}}

    ``save`` publishes atomically (temp+fsync+rename locally; whole-object
    PUT on object stores), then rewrites the manifest — a crash mid-save
    leaves the previous checkpoint fully intact (the property the reference
    gets from rebuildable cache files, `disk_row_iter.h:95-108`).
    """

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.dir = directory
        self.max_to_keep = max_to_keep
        self._store = (_RemoteStore(directory) if "://" in directory
                       else _LocalStore(directory))
        self._pending: Optional[Tuple[Any, List[Any]]] = None  # (thread, box)

    def _name(self, step: int) -> str:
        return f"ckpt-{step}.bin"

    def _path(self, step: int) -> str:
        return self._store.url(self._name(step))

    def _read_manifest(self) -> Dict[str, Any]:
        raw = self._store.read_bytes("MANIFEST.json")
        if raw is None:
            return {"latest": None, "steps": [], "meta": {}}
        try:
            return json_loads(raw.decode())
        except ValueError:
            # truncated/corrupt manifest (crash mid-publish): the published
            # ckpt files are the source of truth — rebuild from them
            steps = sorted(
                int(f[len("ckpt-"):-len(".bin")])
                for f in self._store.names()
                if f.startswith("ckpt-") and f.endswith(".bin")
                and f[len("ckpt-"):-len(".bin")].isdigit())
            log_info("checkpoint: manifest corrupt, rebuilt from %d files",
                     len(steps))
            return {"latest": steps[-1] if steps else None,
                    "steps": steps, "meta": {}}

    def _write_manifest(self, m: Dict[str, Any]) -> None:
        blob = json_dumps(m).encode()
        self._store.write_stream("MANIFEST.json", lambda f: f.write(blob))

    @property
    def steps(self) -> List[int]:
        return list(self._read_manifest()["steps"])

    @property
    def latest_step(self) -> Optional[int]:
        return self._read_manifest()["latest"]

    def save(self, step: int, state: Any,
             meta: Optional[Dict[str, Any]] = None) -> str:
        check(step >= 0, "checkpoint step must be >= 0")
        self._store.write_stream(self._name(step),
                                 lambda f: save_pytree(f, state))
        m = self._read_manifest()
        if step not in m["steps"]:
            m["steps"] = sorted(m["steps"] + [step])
        m["latest"] = max(s for s in m["steps"])
        if meta:
            m["meta"][str(step)] = meta
        # publish the updated manifest FIRST, then unlink pruned files — a
        # crash between the two leaves orphan files (harmless, re-pruned
        # later) rather than a manifest listing steps whose files are gone
        dropped = []
        while len(m["steps"]) > self.max_to_keep:
            drop = m["steps"].pop(0)
            m["meta"].pop(str(drop), None)
            dropped.append(drop)
        self._write_manifest(m)
        for drop in dropped:
            self._store.delete(self._name(drop))
        log_info("checkpoint: saved step %d -> %s", step, self._path(step))
        return self._path(step)

    def save_async(self, step: int, state: Any,
                   meta: Optional[Dict[str, Any]] = None) -> None:
        """Queue :meth:`save` on a background thread and return immediately
        — the TPU-native discipline: the train loop keeps dispatching while
        device→host readback, serialization and the store upload drain off
        the critical path (the async half of what orbax calls
        AsyncCheckpointer; the reference has no analog — its rabit
        CheckPoint is synchronous by design).

        Snapshot semantics: ``jax.Array`` leaves get an async ON-DEVICE
        copy (``jnp.copy`` — an HBM memcpy that dispatches without
        blocking): jax arrays are immutable but a donating train step
        (``make_train_step`` donates params/opt_state) DELETES the old
        buffers on its next call, so capture-by-reference would hand the
        writer dead arrays.  Mutable ``np.ndarray`` leaves are copied NOW
        so a loop that updates host state in place cannot race the writer.
        One save is in flight at a time — a second ``save_async`` first
        waits for (and surfaces errors from) the previous one.  Call
        :meth:`wait` before reading ``latest_step`` or exiting."""
        self.wait()                       # serialize + surface prior errors
        import jax
        import jax.numpy as jnp

        def snap(node):
            # order-preserving walk (jax.tree.map would rebuild dicts in
            # sorted-key order and change the serialized byte layout)
            if isinstance(node, dict):
                out = {k: snap(v) for k, v in node.items()}
                return out if type(node) is dict else type(node)(out)
            if isinstance(node, tuple):
                vals = [snap(v) for v in node]
                return (type(node)(*vals) if hasattr(node, "_fields")
                        else tuple(vals))
            if isinstance(node, list):
                return [snap(v) for v in node]
            if isinstance(node, jax.Array):
                return jnp.copy(node)     # survives donation; async HBM copy
            if isinstance(node, np.ndarray):
                return node.copy()
            # custom registered pytree nodes (dataclass optimizer states,
            # flax structs, …): flatten/unflatten preserves THEIR leaf
            # order, so snapshot semantics hold for every container kind —
            # only plain dicts need the explicit branch above (tree_flatten
            # would re-sort their keys and change the serialized layout)
            leaves, treedef = jax.tree_util.tree_flatten(node)
            if len(leaves) == 1 and leaves[0] is node:
                return node               # true leaf (scalar/str/None/…)
            return jax.tree_util.tree_unflatten(
                treedef, [snap(leaf) for leaf in leaves])

        snapped = snap(state)
        box: List[Any] = []               # [result] or [None, exc]
        import threading

        def run() -> None:
            try:
                box.append(self.save(step, snapped, meta))
            except BaseException as e:  # noqa: BLE001 — surfaced in wait()
                box.append(None)
                box.append(e)

        th = threading.Thread(target=run, name=f"ckpt-save-{step}",
                              daemon=True)
        self._pending = (th, box)
        th.start()

    def wait(self) -> Optional[str]:
        """Block until the pending :meth:`save_async` has published; return
        its checkpoint path (None when nothing was pending).  Re-raises the
        background save's exception, so failures cannot pass silently."""
        pending, self._pending = self._pending, None
        if pending is None:
            return None
        th, box = pending
        th.join()
        if len(box) == 2:
            raise DMLCError(
                f"async checkpoint save failed: {box[1]}") from box[1]
        return box[0]

    def restore(self, step: Optional[int] = None,
                template: Any = None) -> Tuple[int, Any]:
        """-> (step, state). Default: latest. ``template`` restores
        container types (see :func:`load_pytree`) — pass a freshly-built
        state of the same structure to get optax NamedTuples etc. back."""
        m = self._read_manifest()
        if step is None:
            step = m["latest"]
        if step is None:
            raise DMLCError(f"no checkpoints in {self.dir}")
        check(step in m["steps"], f"no checkpoint for step {step}; "
                                  f"have {m['steps']}")
        try:
            f = self._store.open_read(self._name(step))
        except DMLCError as e:
            raise DMLCError(
                f"checkpoint file for step {step} is missing "
                f"({self._path(step)}) — manifest and directory disagree "
                f"(interrupted prune?); pick another step from {m['steps']}"
            ) from e
        with f:
            return step, load_pytree(f, template=template)

    def restore_leaves(self, paths, step: Optional[int] = None
                       ) -> Tuple[int, Dict[str, np.ndarray]]:
        """-> (step, {path: array}) for just the named leaves (see
        :func:`load_pytree_leaves`).  The elastic resharder's fallback:
        when no survivor holds a shard, read THAT leaf — not the whole
        checkpoint — from the last published step."""
        m = self._read_manifest()
        if step is None:
            step = m["latest"]
        if step is None:
            raise DMLCError(f"no checkpoints in {self.dir}")
        check(step in m["steps"], f"no checkpoint for step {step}; "
                                  f"have {m['steps']}")
        with self._store.open_read(self._name(step)) as f:
            return step, load_pytree_leaves(f, paths)

    def meta(self, step: int) -> Dict[str, Any]:
        return self._read_manifest()["meta"].get(str(step), {})


def load_for_inference(directory: str, step: Optional[int] = None,
                       template: Any = None,
                       ) -> Tuple[int, Any, Dict[str, Any]]:
    """Restore just the serving-relevant slice of a training checkpoint:
    ``(step, params, meta)``.

    Training checkpoints carry ``{"params": ..., "opt_state": ...}``
    (dmlc-train) so resume restores optimizer moments; a serving replica
    only needs the params — the opt_state (often the larger half under
    Adam) is dropped immediately after load instead of sitting in the
    server's RSS.  Bare-params checkpoints (anything without a ``params``
    key) pass through whole, so hand-rolled training loops that save the
    param tree directly serve unchanged.  ``meta`` is the manifest entry
    for the restored step (model name etc.) so the caller can refuse a
    checkpoint trained as a different architecture.
    """
    mgr = CheckpointManager(directory)
    if template is not None and "params" not in template:
        template = {"params": template}
    step, state = mgr.restore(step, template=template)
    params = (state["params"]
              if isinstance(state, dict) and "params" in state else state)
    return step, params, mgr.meta(step)


def fast_forward(loader, num_batches: int) -> int:
    """Skip ``num_batches`` batches of a fresh loader to resume mid-epoch
    (the data-position half of resume). Returns batches actually skipped."""
    skipped = 0
    while skipped < num_batches:
        if loader.next_batch() is None:
            break
        skipped += 1
    return skipped
