"""Streaming JSON reader/writer with a typed struct helper.

Capability parity with reference ``include/dmlc/json.h``:

* ``JSONReader``  — incremental pull-reader over a text stream
  (``json.h:41``): ``begin_object``/``next_object_item``,
  ``begin_array``/``next_array_item``, typed reads, line-numbered errors.
* ``JSONWriter``  — push-writer with nesting state (``json.h:152``):
  ``begin_object``/``write_object_keyvalue``/``end_object`` and the array
  equivalents, two-space indentation like the reference's pretty mode.
* ``JSONObjectReadHelper`` — declarative struct reader (``json.h:266``):
  declare required/optional fields, then ``read_all_fields`` enforces
  presence and rejects unknown keys.
* any-valued maps — parity with ``DMLC_JSON_ENABLE_ANY`` (``json.h:338``):
  values tagged with a registered type name round-trip through
  ``register_any_type`` / ``AnyValue``.

The reader is hand-rolled (not ``json.loads``) on purpose: the reference's
value is *streaming* composition — each ``read`` pulls exactly one value, so
huge documents and custom per-field dispatch work without materializing a
tree — plus precise "Line N: ..." errors (``json.h:67-75``).
"""

from __future__ import annotations

import io
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "JSONError",
    "JSONReader",
    "JSONWriter",
    "JSONObjectReadHelper",
    "AnyValue",
    "read_any",
    "register_any_type",
    "json_dumps",
    "json_loads",
]


class JSONError(ValueError):
    """Malformed JSON or schema violation (reference raises CHECK failures
    with line context, ``json.h:67``)."""


class JSONReader:
    """Incremental JSON pull-reader over a text stream (``json.h:41``).

    The cursor contract matches the reference: ``begin_object()`` consumes
    ``{``; each ``next_object_item()`` returns the next key (positioning the
    cursor at its value, which the caller must then read) or ``None`` at
    ``}``. Arrays are symmetric with ``next_array_item() -> bool``.
    """

    def __init__(self, stream) -> None:
        if isinstance(stream, str):
            stream = io.StringIO(stream)
        self._s = stream
        self._peeked: Optional[str] = None
        self._line = 1
        # reference tracks nesting via scope_counter_ (json.h:124-129)
        self._scope: List[Tuple[str, int]] = []

    # -- low-level char pump ------------------------------------------------
    def _getc(self) -> str:
        if self._peeked is not None:
            c, self._peeked = self._peeked, None
        else:
            c = self._s.read(1)
        if c == "\n":
            self._line += 1
        return c

    def _peekc(self) -> str:
        if self._peeked is None:
            self._peeked = self._s.read(1)
        return self._peeked

    def _peek_skip_space(self) -> str:
        while True:
            c = self._peekc()
            if c and c in " \t\r\n":
                self._getc()
            else:
                return c

    def _error(self, msg: str) -> "JSONError":
        return JSONError(f"Line {self._line}: {msg}")

    def _expect(self, ch: str) -> None:
        c = self._peek_skip_space()
        if c != ch:
            raise self._error(f"expected {ch!r}, got {c!r}")
        self._getc()

    # -- scalar reads -------------------------------------------------------
    def read_string(self) -> str:
        self._expect('"')
        out: List[str] = []
        while True:
            c = self._getc()
            if not c:
                raise self._error("unterminated string")
            if c == '"':
                return "".join(out)
            if c == "\\":
                e = self._getc()
                mapped = {'"': '"', "\\": "\\", "/": "/", "n": "\n",
                          "t": "\t", "r": "\r", "b": "\b", "f": "\f"}.get(e)
                if mapped is not None:
                    out.append(mapped)
                elif e == "u":
                    out.append(self._read_u_escape())
                else:
                    raise self._error(f"unknown escape \\{e}")
            else:
                out.append(c)

    def _read_u_escape(self) -> str:
        hexs = "".join(self._getc() for _ in range(4))
        try:
            code = int(hexs, 16)
        except ValueError:
            raise self._error(f"bad \\u escape {hexs!r}")
        # combine UTF-16 surrogate pairs (as stdlib json emits for non-BMP)
        if 0xD800 <= code <= 0xDBFF:
            if self._getc() == "\\" and self._getc() == "u":
                lows = "".join(self._getc() for _ in range(4))
                try:
                    low = int(lows, 16)
                except ValueError:
                    raise self._error(f"bad \\u escape {lows!r}")
                if 0xDC00 <= low <= 0xDFFF:
                    return chr(0x10000 + ((code - 0xD800) << 10)
                               + (low - 0xDC00))
            raise self._error("unpaired surrogate in \\u escape")
        return chr(code)

    def _read_number_token(self) -> str:
        self._peek_skip_space()
        out: List[str] = []
        while True:
            c = self._peekc()
            if c and (c.isdigit() or c in "+-.eE"):
                out.append(self._getc())
            else:
                break
        return "".join(out)

    def read_number(self) -> float:
        text = self._read_number_token()
        try:
            return float(text)
        except ValueError:
            raise self._error(f"invalid number {text!r}")

    def read_int(self) -> int:
        text = self._read_number_token()
        try:
            return int(text)          # exact — no float round-trip
        except ValueError:
            try:
                return int(float(text))
            except ValueError:
                raise self._error(f"invalid number {text!r}")

    def read_bool(self) -> bool:
        c = self._peek_skip_space()
        word = []
        while True:
            c = self._peekc()
            if c and c.isalpha():
                word.append(self._getc())
            else:
                break
        text = "".join(word)
        if text == "true":
            return True
        if text == "false":
            return False
        raise self._error(f"expected bool, got {text!r}")

    def read_null(self) -> None:
        word = []
        self._peek_skip_space()
        while True:
            c = self._peekc()
            if c and c.isalpha():
                word.append(self._getc())
            else:
                break
        if "".join(word) != "null":
            raise self._error("expected null")

    # -- composite cursors (json.h:82-110) ----------------------------------
    def begin_object(self) -> None:
        self._expect("{")
        self._scope.append(("{", 0))

    def begin_array(self) -> None:
        self._expect("[")
        self._scope.append(("[", 0))

    def next_object_item(self) -> Optional[str]:
        kind, count = self._scope[-1]
        assert kind == "{"
        c = self._peek_skip_space()
        if c == "}":
            self._getc()
            self._scope.pop()
            return None
        if count > 0:
            if c != ",":
                raise self._error(f"expected ',' between items, got {c!r}")
            self._getc()
            self._peek_skip_space()
        key = self.read_string()
        self._expect(":")
        self._scope[-1] = (kind, count + 1)
        return key

    def next_array_item(self) -> bool:
        kind, count = self._scope[-1]
        assert kind == "["
        c = self._peek_skip_space()
        if c == "]":
            self._getc()
            self._scope.pop()
            return False
        if count > 0:
            if c != ",":
                raise self._error(f"expected ',' between items, got {c!r}")
            self._getc()
        self._scope[-1] = (kind, count + 1)
        return True

    # -- generic value read (type-dispatched like Handler<T>, json.h:383+) --
    def read(self) -> Any:
        c = self._peek_skip_space()
        if c == '"':
            return self.read_string()
        if c == "{":
            out: Dict[str, Any] = {}
            self.begin_object()
            while True:
                key = self.next_object_item()
                if key is None:
                    return out
                out[key] = self.read()
        if c == "[":
            arr: List[Any] = []
            self.begin_array()
            while self.next_array_item():
                arr.append(self.read())
            return arr
        if c in "tf":
            return self.read_bool()
        if c == "n":
            return self.read_null()
        if c == "" :
            raise self._error("unexpected end of input")
        text = self._read_number_token()
        try:
            # ints stay exact (no float round-trip: 10**17+1 must survive)
            if text.lstrip("+-").isdigit():
                return int(text)
            return float(text)
        except ValueError:
            raise self._error(f"invalid number {text!r}")


class JSONWriter:
    """Streaming JSON writer with reference-style pretty printing
    (``json.h:152``; two-space indent per scope like ``WriteSeperator``
    ``json.h:549``)."""

    def __init__(self, stream=None) -> None:
        self._s = stream if stream is not None else io.StringIO()
        self._scope: List[int] = []  # item count per open scope

    def getvalue(self) -> str:
        return self._s.getvalue()

    def _sep(self) -> None:
        if self._scope:
            self._s.write("\n" + "  " * len(self._scope))

    _STR_ESC = {"\\": "\\\\", '"': '\\"', "\n": "\\n", "\t": "\\t",
                "\r": "\\r", "\b": "\\b", "\f": "\\f"}

    def write_string(self, v: str) -> None:
        out = ['"']
        for c in v:
            esc = self._STR_ESC.get(c)
            if esc is not None:
                out.append(esc)
            elif c < "\x20":
                out.append(f"\\u{ord(c):04x}")
            else:
                out.append(c)
        out.append('"')
        self._s.write("".join(out))

    def write_number(self, v) -> None:
        if isinstance(v, bool):
            self._s.write("true" if v else "false")
        elif isinstance(v, int):
            self._s.write(str(v))
        else:
            f = float(v)
            if f != f or f in (float("inf"), float("-inf")):
                raise JSONError(f"non-finite float {f!r} is not valid JSON")
            self._s.write(repr(f))

    def begin_object(self) -> None:
        self._s.write("{")
        self._scope.append(0)

    def end_object(self) -> None:
        n = self._scope.pop()
        if n:
            self._s.write("\n" + "  " * len(self._scope))
        self._s.write("}")

    def begin_array(self) -> None:
        self._s.write("[")
        self._scope.append(0)

    def end_array(self) -> None:
        n = self._scope.pop()
        if n:
            self._s.write("\n" + "  " * len(self._scope))
        self._s.write("]")

    def write_object_keyvalue(self, key: str, value: Any) -> None:
        if self._scope[-1] > 0:
            self._s.write(",")
        self._scope[-1] += 1
        self._sep()
        self.write_string(key)
        self._s.write(": ")
        self.write(value)

    def write_array_item(self, value: Any) -> None:
        if self._scope[-1] > 0:
            self._s.write(",")
        self._scope[-1] += 1
        self._sep()
        self.write(value)

    def write(self, value: Any) -> None:
        if isinstance(value, AnyValue):
            _write_any(self, value)
        elif isinstance(value, str):
            self.write_string(value)
        elif isinstance(value, bool) or isinstance(value, (int, float)):
            self.write_number(value)
        elif value is None:
            self._s.write("null")
        elif isinstance(value, dict):
            self.begin_object()
            for k, v in value.items():
                self.write_object_keyvalue(str(k), v)
            self.end_object()
        elif isinstance(value, (list, tuple)):
            self.begin_array()
            for v in value:
                self.write_array_item(v)
            self.end_array()
        elif hasattr(value, "write_json"):
            # streaming hook: obj.write_json(writer) emits its own JSON
            # (distinct from parameter.py's save_json(self) -> str)
            value.write_json(self)
        else:
            raise TypeError(f"cannot JSON-serialize {type(value).__name__}")


class JSONObjectReadHelper:
    """Declarative struct reader (``json.h:266``): declare fields with
    per-field read functions, then ``read_all_fields`` walks one object,
    dispatching each key, erroring on unknown keys and missing required
    fields — the same contract as ``DeclareField``/``ReadAllFields``
    (``json.h:285-334``)."""

    def __init__(self) -> None:
        # key -> (optional, read_fn, default)
        self._fields: Dict[str, Tuple[bool, Callable[[JSONReader], Any], Any]] = {}
        self.values: Dict[str, Any] = {}

    def declare_field(self, key: str,
                      read_fn: Optional[Callable[[JSONReader], Any]] = None,
                      optional: bool = False,
                      default: Any = None) -> None:
        self._fields[key] = (optional, read_fn or (lambda r: r.read()), default)

    def declare_optional_field(self, key: str,
                               read_fn: Optional[Callable[[JSONReader], Any]] = None,
                               default: Any = None) -> None:
        self.declare_field(key, read_fn, optional=True, default=default)

    def read_all_fields(self, reader: JSONReader) -> Dict[str, Any]:
        # fresh state per record — a reused helper must not leak prior values
        self.values = {k: d for k, (opt, _, d) in self._fields.items() if opt}
        seen = set()
        reader.begin_object()
        while True:
            key = reader.next_object_item()
            if key is None:
                break
            if key not in self._fields:
                raise JSONError(f"JSONReader: unknown field {key!r}")
            seen.add(key)
            self.values[key] = self._fields[key][1](reader)
        for key, (optional, _, _) in self._fields.items():
            if not optional and key not in seen:
                raise JSONError(f"JSONReader: missing required field {key!r}")
        return self.values


# -- any-valued maps (DMLC_JSON_ENABLE_ANY parity, json.h:338,700-760) -------

class AnyValue:
    """Type-erased JSON value tagged with a registered type name — the
    Python face of ``dmlc::any`` inside JSON maps (``json.h:700``)."""

    __slots__ = ("type_name", "value")

    def __init__(self, type_name: str, value: Any) -> None:
        self.type_name = type_name
        self.value = value

    def __eq__(self, other) -> bool:
        return (isinstance(other, AnyValue)
                and other.type_name == self.type_name
                and other.value == self.value)

    def __repr__(self) -> str:
        return f"AnyValue({self.type_name!r}, {self.value!r})"


_ANY_TYPES: Dict[str, Tuple[Callable[[Any], Any], Callable[[Any], Any]]] = {}


def register_any_type(name: str,
                      to_json: Callable[[Any], Any] = lambda v: v,
                      from_json: Callable[[Any], Any] = lambda v: v) -> None:
    """Register codec for a type name used in any-valued maps
    (``DMLC_JSON_REGISTER_ANY`` analog, ``json.h:347``)."""
    _ANY_TYPES[name] = (to_json, from_json)


def _write_any(writer: JSONWriter, v: AnyValue) -> None:
    if v.type_name not in _ANY_TYPES:
        raise JSONError(f"any type {v.type_name!r} not registered")
    to_json, _ = _ANY_TYPES[v.type_name]
    writer.begin_array()
    writer.write_array_item(v.type_name)
    writer.write_array_item(to_json(v.value))
    writer.end_array()


def read_any(reader: JSONReader) -> AnyValue:
    """Read one ``[type_name, value]`` pair written by ``_write_any``."""
    reader.begin_array()
    if not reader.next_array_item():
        raise JSONError("empty any value")
    name = reader.read_string()
    if name not in _ANY_TYPES:
        raise JSONError(f"any type {name!r} not registered")
    if not reader.next_array_item():
        raise JSONError("any value missing payload")
    _, from_json = _ANY_TYPES[name]
    value = from_json(reader.read())
    if reader.next_array_item():
        raise JSONError("trailing data in any value")
    return AnyValue(name, value)


# -- convenience ------------------------------------------------------------

def json_dumps(value: Any) -> str:
    w = JSONWriter()
    w.write(value)
    return w.getvalue()


def json_loads(text: str) -> Any:
    return JSONReader(text).read()
