"""Logging and check macros — capability parity with reference ``include/dmlc/logging.h``.

The reference provides glog-compatible ``CHECK*``/``LOG(severity)`` macros with
throw-on-fatal (`logging.h:104-155,255`, ``DMLC_LOG_FATAL_THROW`` `base.h:20`),
a customizable sink (``DMLC_LOG_CUSTOMIZE`` `logging.h:142`), and a date logger
(`logging.h:178`).  The TPU-native equivalent is a thin layer over Python
``logging`` with:

* ``check(cond, msg)`` / ``check_eq`` / ``check_ne`` / ... raising
  :class:`DMLCError` (analog of ``dmlc::Error`` `logging.h:26`),
* ``LOG`` helpers with INFO/WARNING/ERROR/FATAL severities where FATAL raises,
* a pluggable sink via :func:`set_log_sink` (analog of ``DMLC_LOG_CUSTOMIZE``).
"""

from __future__ import annotations

# dmlclint: disable-file=env-discipline -- this module bootstraps before
# utils.parameter (which imports it for log_warning); routing its DMLC_*
# reads through the helpers would be a circular import.  The knobs are
# still inventoried/documented via the helper-based readers elsewhere.

import json
import logging as _pylogging
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "DMLCError",
    "ParamError",
    "check",
    "check_eq",
    "check_ne",
    "check_lt",
    "check_le",
    "check_gt",
    "check_ge",
    "check_notnull",
    "log_info",
    "log_warning",
    "log_error",
    "log_fatal",
    "set_log_sink",
    "set_log_context",
    "get_log_tail",
    "get_logger",
    "IdOverflowError",
]


class DMLCError(RuntimeError):
    """Base error type (reference ``dmlc::Error``, `logging.h:26`)."""


class ParamError(DMLCError, ValueError):
    """Raised when parameter initialization fails (reference `parameter.h:62`)."""


class IdOverflowError(DMLCError, ValueError):
    """A feature id exceeds int32 range on the device path and no feature
    hashing (``id_mod``) is configured.  The reference keeps uint64 ids
    first-class (`src/data.cc:131-147`); the TPU batch layout is int32, so
    wide ids must be hashed or the layout widened — never silently wrapped."""


_logger = _pylogging.getLogger("dmlc_core_tpu")
if not _logger.handlers:
    _h = _pylogging.StreamHandler(sys.stderr)
    _h.setFormatter(_pylogging.Formatter("[%(asctime)s] %(levelname)s %(message)s", "%H:%M:%S"))
    _logger.addHandler(_h)
    _level = os.environ.get("DMLC_LOG_LEVEL", "INFO").upper()
    _logger.setLevel(_level if _level in ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL") else "INFO")

# Pluggable sink: fn(severity: str, message: str) -> None.  When set, replaces
# the default python-logging emission (reference DMLC_LOG_CUSTOMIZE, logging.h:142-146).
_custom_sink: Optional[Callable[[str, str], None]] = None


def get_logger() -> _pylogging.Logger:
    return _logger


def set_log_sink(sink: Optional[Callable[[str, str], None]]) -> None:
    """Install a custom log sink, or None to restore the default."""
    global _custom_sink
    _custom_sink = sink


# Process-wide log correlation fields.  ``rank`` is set by the collective
# layer once the tracker assigns it (env DMLC_RANK seeds launcher-spawned
# processes); the live trace id is looked up per record.
#
# Writers (collective registration, server startup, worker threads) can
# race each other and the readers in every logging call, so updates go
# through copy-on-write under a lock: readers grab the dict reference
# once — always a complete, immutable-by-convention mapping — and never
# observe a half-applied update.
_log_ctx: Dict[str, Any] = {}
_log_ctx_lock = threading.Lock()
_r = os.environ.get("DMLC_RANK")
if _r is not None and _r.lstrip("-").isdigit():
    _log_ctx["rank"] = int(_r)
del _r

# In-process tail ring for the flight recorder: every emitted line, post
# context-stamping, bounded by DMLC_LOG_TAIL (deque handles its own
# locking for append; snapshots copy under the ctx lock for a stable view).
_log_tail: deque = deque(
    maxlen=max(1, int(os.environ.get("DMLC_LOG_TAIL", "256") or 256)))


def set_log_context(**fields: Any) -> None:
    """Attach correlation fields (``rank=...``) to every subsequent log
    record; ``None`` removes a field.  Safe under concurrent threads:
    the context dict is replaced wholesale, never mutated in place."""
    global _log_ctx
    with _log_ctx_lock:
        ctx = dict(_log_ctx)
        for k, v in fields.items():
            if v is None:
                ctx.pop(k, None)
            else:
                ctx[k] = v
        _log_ctx = ctx


def get_log_tail() -> List[str]:
    """The last N emitted log lines (N = ``DMLC_LOG_TAIL``, default 256),
    oldest first — what the flight recorder snapshots into a bundle."""
    with _log_ctx_lock:
        return list(_log_tail)


def _live_trace_id() -> Optional[str]:
    """Active trace id, if the telemetry plane is loaded AND a trace is
    live on this logical thread.  Looked up via sys.modules so logging —
    imported by everything — never imports telemetry (which imports
    utils back): the cost when telemetry is unused is one dict miss."""
    mod = sys.modules.get("dmlc_core_tpu.telemetry.trace")
    if mod is None:
        return None
    try:
        return mod.current_trace_id()
    except Exception:
        return None


def _record_fields(severity: str, msg: str) -> Dict[str, Any]:
    rec: Dict[str, Any] = {
        "ts": time.time(), "level": severity, "msg": msg}
    # one reference read: set_log_context swaps the whole dict, so this
    # view is always internally consistent without taking the lock
    rec.update(_log_ctx)
    trace_id = _live_trace_id()
    if trace_id is not None:
        rec["trace_id"] = trace_id
    return rec


def _emit(severity: str, msg: str) -> None:
    rec = _record_fields(severity, msg)
    if os.environ.get("DMLC_LOG_FORMAT", "").lower() == "json":
        # JSON-lines for log shippers: write the line directly (the text
        # formatter's "[time] LEVEL " prefix would corrupt the JSON)
        line = json.dumps(rec, default=str)
        _log_tail.append(line)
        if _custom_sink is not None:
            _custom_sink(severity, line)
        else:
            print(line, file=sys.stderr, flush=True)
        return
    suffix = " ".join(f"{k}={v}" for k, v in rec.items()
                      if k not in ("ts", "level", "msg"))
    if suffix:
        msg = f"{msg} [{suffix}]"
    _log_tail.append(
        time.strftime("%H:%M:%S", time.localtime(rec["ts"]))
        + f" {severity} {msg}")
    if _custom_sink is not None:
        _custom_sink(severity, msg)
        return
    level = getattr(_pylogging, severity, _pylogging.INFO)
    _logger.log(level, msg)


def log_info(msg: str, *args: Any) -> None:
    _emit("INFO", msg % args if args else msg)


def log_warning(msg: str, *args: Any) -> None:
    _emit("WARNING", msg % args if args else msg)


def log_error(msg: str, *args: Any) -> None:
    _emit("ERROR", msg % args if args else msg)


def log_fatal(msg: str, *args: Any) -> None:
    """FATAL logs raise (reference throw-on-fatal ``LogMessageFatal`` `logging.h:255`)."""
    text = msg % args if args else msg
    _emit("ERROR", text)
    raise DMLCError(text)


def check(cond: Any, msg: str = "") -> None:
    """Reference ``CHECK(x)`` `logging.h:104`: raise DMLCError when cond is falsy."""
    if not cond:
        raise DMLCError(f"Check failed: {msg}" if msg else "Check failed")


def _check_bin(op_name: str, ok: bool, x: Any, y: Any, msg: str) -> None:
    if not ok:
        detail = f"Check failed: {x!r} {op_name} {y!r}"
        if msg:
            detail += f": {msg}"
        raise DMLCError(detail)


def check_eq(x: Any, y: Any, msg: str = "") -> None:
    _check_bin("==", x == y, x, y, msg)


def check_ne(x: Any, y: Any, msg: str = "") -> None:
    _check_bin("!=", x != y, x, y, msg)


def check_lt(x: Any, y: Any, msg: str = "") -> None:
    _check_bin("<", x < y, x, y, msg)


def check_le(x: Any, y: Any, msg: str = "") -> None:
    _check_bin("<=", x <= y, x, y, msg)


def check_gt(x: Any, y: Any, msg: str = "") -> None:
    _check_bin(">", x > y, x, y, msg)


def check_ge(x: Any, y: Any, msg: str = "") -> None:
    _check_bin(">=", x >= y, x, y, msg)


def check_notnull(x: Any, msg: str = "") -> Any:
    """Reference ``CHECK_NOTNULL`` `logging.h:119`."""
    if x is None:
        raise DMLCError(f"Check notnull failed: {msg}" if msg else "Check notnull failed")
    return x


class PeriodicLogger:
    """Rate-limited progress logger for throughput reporting.

    Mirrors the reference's every-10MB / every-N-seconds ingest progress logs
    (`basic_row_iter.h:68-76`, `disk_row_iter.h:117-126`).
    """

    def __init__(self, period_sec: float = 2.0):
        self.period_sec = period_sec
        self._last = time.monotonic()

    def maybe(self, msg_fn: Callable[[], str]) -> None:
        now = time.monotonic()
        if now - self._last >= self.period_sec:
            self._last = now
            log_info(msg_fn())
