"""Orbax interop for the checkpoint substrate.

:class:`~dmlc_core_tpu.utils.checkpoint.CheckpointManager` is the native
path — URI-addressed (file/s3/gs/hdfs through the io layer), atomic
versioned publishes, template restore, data fast-forward (the reference's
Serializable/serializer substrate, `include/dmlc/io.h:112`, expressed for
pytrees).  This module bridges to orbax — the JAX ecosystem's standard
checkpointer — so dmlc_core_tpu state drops into deployments that already
manage checkpoints with orbax (multi-host array gathering, async saves),
and orbax-managed state loads back into our managers.

Kept deliberately thin: two functions, no policy.
"""

from __future__ import annotations

import os
from typing import Any, Optional

__all__ = ["save_orbax", "restore_orbax"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_orbax(path: str, tree: Any, *, force: bool = True) -> None:
    """Write ``tree`` (a pytree of arrays) as an orbax checkpoint at the
    local directory ``path``.  For URI-addressed / versioned checkpoints
    use :class:`CheckpointManager`; this is the ecosystem-interop escape
    hatch."""
    import jax
    import numpy as np
    # older orbax StandardCheckpointers reject numpy scalar leaves
    # (np.int64 et al.); the equivalent 0-d ndarray is accepted by every
    # version and restores to the same value
    tree = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, np.generic) else x, tree)
    ckpt = _checkpointer()
    ckpt.save(os.path.abspath(path), tree, force=force)
    # StandardCheckpointer saves asynchronously; the contract here is
    # durability-on-return (matching CheckpointManager's atomic publish)
    ckpt.wait_until_finished()


def restore_orbax(path: str, template: Optional[Any] = None) -> Any:
    """Read an orbax checkpoint.  ``template`` (a pytree of arrays or
    ShapeDtypeStructs) pins structure/dtypes/shardings the way
    ``load_pytree(template=...)`` does for the native format."""
    ckpt = _checkpointer()
    path = os.path.abspath(path)
    if template is None:
        return ckpt.restore(path)
    return ckpt.restore(path, template)
