"""Core utilities layer (capability parity with reference ``include/dmlc/``, SURVEY §2.1)."""

from .logging import (  # noqa: F401
    DMLCError, ParamError, IdOverflowError,
    check, check_eq, check_ne, check_lt, check_le, check_gt, check_ge,
    check_notnull, log_info, log_warning, log_error, log_fatal,
    set_log_sink, set_log_context, get_logger, PeriodicLogger,
)
from .registry import Registry, RegistryEntry  # noqa: F401
from .parameter import Parameter, field, FieldEntry, get_env  # noqa: F401
from .config import Config  # noqa: F401
from .threaded_iter import ThreadedIter  # noqa: F401
from .timer import get_time, Timer  # noqa: F401
from . import serializer  # noqa: F401
from .concurrency import (  # noqa: F401
    ConcurrentBlockingQueue, Spinlock, ThreadLocalStore, ObjectPool,
)
from .memory_io import MemoryFixedSizeStream, MemoryStringStream  # noqa: F401
from .common import split, hash_combine, byteswap  # noqa: F401
from .checkpoint import (  # noqa: F401
    Serializable, CheckpointManager, save_pytree, load_pytree, fast_forward,
    load_for_inference,
)
from .orbax_compat import save_orbax, restore_orbax  # noqa: F401
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, ThroughputMeter, StageTimer, MetricsRegistry,
    metrics, trace_span, profile_trace,
)
from .retry import (  # noqa: F401
    Deadline, DeadlineExpired, RetryPolicy, RetriesExhausted,
    CircuitBreaker, CircuitOpen,
)
from .faults import (  # noqa: F401
    FaultInjected, FaultSpecError, fault_point, install_faults,
    clear_faults, inject_faults,
)
from .json import (  # noqa: F401
    JSONReader, JSONWriter, JSONObjectReadHelper, AnyValue,
    register_any_type, read_any, json_dumps, json_loads,
)
