"""Benchmark: libsvm ingest → fixed-shape device batches, vs the reference.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "MB/s", "vs_baseline": R}

* value: end-to-end throughput of THIS framework's pipeline — InputSplit →
  native parse → CSR RowBlock → fixed-shape pack → jax.device_put into
  HBM (our path does strictly more than the baseline: the baseline stops at
  host CSR).
* vs_baseline: ratio against the reference dmlc-core's own
  ``libsvm_parser_test`` (`test/libsvm_parser_test.cc`) compiled from
  /root/reference and run on the same file and host.  If the reference can't
  be built here, falls back to a recorded baseline constant measured on this
  image (175 MB/s single-core).

The TPU is probed in a subprocess first: a wedged tunnel must degrade to CPU
rather than hang the bench.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
# process-start anchor for the probe's soft deadline (DMLC_BENCH_DEADLINE_S)
_T0 = time.monotonic()
DATA = "/tmp/dmlc_bench_data.libsvm"
REF_BIN = "/tmp/dmlc_bench_refbuild/ref_libsvm_test"
FALLBACK_BASELINE_MBS = 175.0  # reference on this image, 1 core (see above)
TARGET_MB = int(os.environ.get("DMLC_BENCH_MB", "150"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def host_cores() -> int:
    """Usable cores (affinity-aware; the bench host may be pinned)."""
    try:
        return len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return os.cpu_count() or 1


def gen_data() -> None:
    if os.path.exists(DATA) and os.path.getsize(DATA) >= TARGET_MB * 0.9 * (1 << 20):
        return
    import numpy as np
    log(f"generating ~{TARGET_MB}MB synthetic libsvm at {DATA} ...")
    rng = np.random.default_rng(0)
    with open(DATA, "wb") as f:
        written = 0
        while written < TARGET_MB * (1 << 20):
            rows = []
            for i in range(20000):
                n = int(rng.integers(5, 40))
                idx = np.sort(rng.choice(1_000_000, size=n, replace=False))
                vals = rng.random(n)
                rows.append(b"%d " % (i & 1) + b" ".join(
                    b"%d:%.4f" % (j, v) for j, v in
                    zip(idx.tolist(), vals.tolist())))
            blob = b"\n".join(rows) + b"\n"
            f.write(blob)
            written += len(blob)


def measure_reference() -> float:
    """Build (cached) and run the reference's own libsvm throughput test.

    Returns 0.0 when the reference can't be built/run (caller falls back)."""
    try:
        if not os.path.exists(REF_BIN):
            os.makedirs(os.path.dirname(REF_BIN), exist_ok=True)
            srcs = [
                "test/libsvm_parser_test.cc", "src/io.cc", "src/data.cc",
                "src/recordio.cc", "src/io/line_split.cc",
                "src/io/recordio_split.cc", "src/io/indexed_recordio_split.cc",
                "src/io/input_split_base.cc", "src/io/filesys.cc",
                "src/io/local_filesys.cc",
            ]
            cmd = (["g++", "-O3", "-std=c++11", "-fopenmp",
                    "-I/root/reference/include"]
                   + [f"/root/reference/{s}" for s in srcs]
                   + ["-o", REF_BIN])
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        nthread = max(1, (os.cpu_count() or 1))
        out = subprocess.run(
            [REF_BIN, DATA, "0", "1", str(nthread)],
            capture_output=True, text=True, timeout=600)
        # last line: "N examples, M MB read, X MB/sec"
        last = (out.stderr + out.stdout).strip().splitlines()[-1]
        mbs = float(last.split(",")[-1].strip().split()[0])
        log(f"reference baseline: {mbs:.1f} MB/s ({nthread} threads)")
        return mbs
    except Exception as e:  # noqa: BLE001
        log(f"reference build/run unavailable ({e})")
        return 0.0


def _probe_subprocess(code: str, timeout_s: int, label: str) -> bool:
    """Run one probe snippet in a subprocess (a wedged tunnel can't hang
    us); True iff it printed a non-cpu platform and exited 0."""
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache"))
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s, env=env)
        plat = (out.stdout.strip().splitlines()[-1]
                if out.stdout.strip() else "")
        ok = out.returncode == 0 and plat not in ("", "cpu")
        log(f"tpu probe [{label}]: rc={out.returncode} "
            f"platform={plat!r} → {'TPU' if ok else 'no grant'}")
        if not ok and out.stderr:
            log("probe stderr tail: " + out.stderr[-500:])
        return ok
    except subprocess.TimeoutExpired as e:
        tail = ""
        if e.stderr:
            err = e.stderr
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            tail = "; stderr tail: " + err[-500:]
        log(f"tpu probe [{label}] timed out after {timeout_s}s{tail}")
        return False


# tiny-put grant check: device discovery + one 4-byte put + a VALUE read
# (the only completion proof the tunnel honors) — no matmul, no jit compile
_GRANT_CODE = ("import jax, numpy as np;"
               "d=jax.devices();"
               "h=jax.device_put(np.int32(7), d[0]);"
               "assert int(np.asarray(h))==7;"
               "print(d[0].platform)")
_FULL_CODE = ("import jax, jax.numpy as jnp;"
              "d=jax.devices();"
              "x=jnp.ones((256,256));"
              "(x@x).block_until_ready();"
              "print(d[0].platform)")


def probe_tpu(timeout_s: int = 0) -> bool:
    """Two-stage TPU probe (VERDICT r4 #5: a driver run must either land
    on TPU or fall back in minutes, not ~20).

    Stage 1 — fast-fail grant check: tiny put + value read, SHORT attempts
    (``DMLC_TPU_PROBE_FAST_S``, default 60 s each) looped until a total
    fast window (``DMLC_TPU_PROBE_FAST_TOTAL_S``, default 240 s) runs out.
    A dead tunnel fails in ≤~4 min instead of eating two 600 s heavy-probe
    timeouts (r4's official artifact fell back to CPU exactly that way),
    while a claim QUEUED behind other tenants — the round-1 postmortem
    case — still lands any time inside the window, because each attempt
    re-enters the claim queue rather than giving up after one try.  Set
    ``DMLC_TPU_PROBE_FAST_S=0`` to skip straight to the patient probe
    (the harvest loop's retry cadence makes its own budget via
    ``DMLC_TPU_PROBE_S``).

    Stage 2 — full check (compile + matmul) under the patient budget
    (``DMLC_TPU_PROBE_S``, default 600 s): only runs once stage 1 proved a
    grant exists, so its budget is spent on compile/queue time, not on
    discovering a dead link."""
    if os.environ.get("DMLC_FORCE_CPU") == "1":
        log("DMLC_FORCE_CPU=1 → skipping TPU probe")
        return False
    if timeout_s <= 0:
        timeout_s = int(os.environ.get("DMLC_TPU_PROBE_S", "600"))
    fast_s = int(os.environ.get("DMLC_TPU_PROBE_FAST_S", "60"))
    if fast_s > 0:
        fast_total = float(os.environ.get("DMLC_TPU_PROBE_FAST_TOTAL_S",
                                          "240"))
        fast_deadline = time.monotonic() + fast_total
        granted = False
        attempt = 0
        while not granted:
            attempt += 1
            budget = min(fast_s, max(5, int(fast_deadline
                                            - time.monotonic())))
            granted = _probe_subprocess(
                _GRANT_CODE, budget, f"grant-check {attempt}")
            if not granted and time.monotonic() >= fast_deadline:
                log(f"→ CPU fallback (no grant in {attempt} checks over "
                    f"{fast_total:.0f}s fast window)")
                return False
    for attempt in range(2):
        if _probe_subprocess(_FULL_CODE, timeout_s, f"full {attempt + 1}"):
            return True
    log("→ CPU fallback")
    return False


def require_tpu_or_exit(platform: str) -> None:
    """The DMLC_REQUIRE_TPU=1 contract, shared by every harvest script:
    never write cpu numbers under a tpu-named artifact — exit 9 (which
    harvest_run.sh treats as 'grant lost, abort') when the backend fell
    back to cpu."""
    if os.environ.get("DMLC_REQUIRE_TPU") == "1" and platform == "cpu":
        log("DMLC_REQUIRE_TPU=1 and no TPU → exiting 9")
        sys.exit(9)


def measure_link_verified(mb: int = 16, reps: int = 3) -> float:
    """Verified single-stream h2d rate: per-rep mutated bytes (the tunnel
    runtime dedupes identical puts) and a d2h value read of EVERY put
    handle as the only accepted completion proof (ready-futures resolve
    early — see consume_batch; same policy as tpu_diag.bench_put_bw /
    bench_put_streams, the canonical link probes).  The per-handle reads
    sit inside the window, so this is a conservative lower bound (~1 RTT
    per rep).  Returns MB/s, or 0.0 if anything fails (the caller treats
    the link measurement as optional context)."""
    try:
        import jax
        import numpy as np
        dev = jax.devices()[0]
        base = np.arange(mb * (1 << 20) // 4, dtype=np.int32)
        h = jax.device_put(base, dev)                      # warm
        int(np.asarray(h[:1])[0])
        # one IMMUTABLE host array per rep: mutating a shared buffer
        # between async puts would let a zero-copy/aliasing runtime
        # snapshot a later rep's bytes into an earlier in-flight put,
        # weakening the distinct-bytes dedupe defense; per-rep arrays
        # stay untouched until their completion read
        bufs = []
        for rep in range(reps):
            b = base.copy()
            b[0] = -rep - 1
            bufs.append(b)
        t0 = time.perf_counter()
        handles = [jax.device_put(b, dev) for b in bufs]
        for rep, h in enumerate(handles):  # completion proof, every put
            if int(np.asarray(h[:1])[0]) != -rep - 1:
                log("link probe: sentinel mismatch — dedupe suspected")
                return 0.0
        dt = time.perf_counter() - t0
        return reps * mb / dt
    except Exception as e:  # noqa: BLE001
        log(f"link probe failed ({type(e).__name__}: {e}) — omitting")
        return 0.0


def consume_batch(acc, batch):
    """Fold one device batch into a 1-element on-device accumulator.
    Timed ingest loops thread every batch through this so that
    ``prove_consumed`` — a d2h VALUE read of the accumulator — can only
    resolve once every batch actually landed on the device.
    ``block_until_ready`` is not that proof on the tunnel runtime: its
    ready-futures can resolve before remote execution/transfer finishes
    (2026-07-31 window: 15222 TFLOP/s on a ~394-peak chip; 573k rows/s
    submitted vs 72k completed).  The per-batch add is async — no host
    blocking inside the timed loop."""
    v = batch["vals"].ravel()[0]
    return v if acc is None else acc + v


def prove_consumed(acc) -> None:
    """End a timed ingest window: value read-back of the accumulator."""
    if acc is not None:
        float(acc)


def force_cpu() -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge
        reg = getattr(xla_bridge, "_backend_factories", None)
        if isinstance(reg, dict):
            reg.pop("axon", None)
    except Exception:
        pass


def measure_ours(platform_override: str = "", interleave=None):
    """Returns (mean_mbps, per_run_mbps, (put_threads, compact, rows),
    platform).

    ``platform_override`` forces the config-probe control flow of another
    platform while running on the current backend — the multi-combo TPU
    probe path must be exercisable in CPU tests, or a bug in it would
    surface for the first time during the one driver run that matters."""
    sys.path.insert(0, REPO)
    from dmlc_core_tpu import native
    if not native.available():
        native.build()
    import jax
    from dmlc_core_tpu.data import create_parser
    from dmlc_core_tpu.pipeline import DeviceLoader
    from dmlc_core_tpu.utils.metrics import metrics

    size_mb = os.path.getsize(DATA) / (1 << 20)
    platform = platform_override or jax.devices()[0].platform
    log(f"running ingest on {platform} ...")
    batch_rows = int(os.environ.get("DMLC_BENCH_ROWS", "16384"))
    nnz_cap = int(os.environ.get("DMLC_BENCH_NNZ", str(512 * 1024)))

    cores = host_cores()
    # on a single core the extra parse thread + OpenMP team only add
    # context-switch overhead; on real hosts they scale the parse
    nthreads, threaded = (1, False) if cores == 1 else (cores, True)
    log(f"parser config: nthreads={nthreads} threaded={threaded} "
        f"({cores} cores)")

    prefetch = int(os.environ.get("DMLC_BENCH_PREFETCH", "4"))

    def run_once(put_threads: int = 1, compact: bool = False,
                 rows: int = 0, nnz: int = 0) -> float:
        import resource
        metrics.reset()
        parser = create_parser(DATA, 0, 1, "libsvm", nthreads=nthreads,
                               threaded=threaded)
        loader = DeviceLoader(parser, batch_rows=rows or batch_rows,
                              nnz_cap=nnz or nnz_cap, prefetch=prefetch,
                              put_threads=put_threads, wire_compact=compact)
        nbatches = 0
        acc = None
        t0 = time.perf_counter()
        c0 = time.process_time()
        for batch in loader:
            acc = consume_batch(acc, batch)   # completion-proof accumulator
            nbatches += 1
        prove_consumed(acc)
        dt = time.perf_counter() - t0
        cpu = time.process_time() - c0
        loader.close()
        log(f"  {nbatches} device batches in {dt:.2f}s "
            f"({size_mb / dt:.1f} MB/s, cpu {cpu:.2f}s)")
        # stage breakdown (VERDICT r1 #2) + degradation telemetry
        # (VERDICT r2 weak#1: live-buffer counts per run)
        try:
            parts = []
            # h2d_pool: concurrent workers' overlapping seconds (pt>1)
            for name in ("parser.chunk", "parser.parse",
                         "device_loader.pack",
                         "device_loader.cache_read",
                         "device_loader.cache_write",
                         "device_loader.h2d",
                         "device_loader.h2d_pool"):
                st = metrics.stage(name)
                parts.append(f"{name}={st.total_sec:.2f}s")
            log("  stages: " + " ".join(parts))
            rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
            log(f"  live jax arrays: {len(jax.live_arrays())}, "
                f"peak rss: {rss_mb:.0f} MB")
        except Exception as e:  # noqa: BLE001
            log(f"  (stage breakdown unavailable: {e})")
        return size_mb / dt

    if cores > 1:
        # multi-thread parse scaling evidence (VERDICT r2 #7): same bytes,
        # nt=1 vs nt=cores through the native OpenMP chunk parser
        with open(DATA, "rb") as f:
            blob = f.read(64 << 20)
        for nt in (1, cores):
            t0 = time.perf_counter()
            native.parse_libsvm(blob, nthreads=nt)
            dt = time.perf_counter() - t0
            log(f"  parse scaling: nt={nt} → "
                f"{len(blob) / (1 << 20) / dt:.1f} MB/s")
    pt_env = os.environ.get("DMLC_BENCH_PUT_THREADS")
    cm_env = os.environ.get("DMLC_BENCH_COMPACT")
    # pt grid [4, 2, 1], best-guess-first: pt=4 won every r4 e2e probe
    # (73.7 vs 61.0 at pt=2 in the 05:1x window) even though the RAW
    # synchronized-stream diag peaks at 2 streams (43.1 vs 33.9 MB/s,
    # TPU_DIAG_r04) — the loader's staggered puts overlap pack/transfer
    # phases, so more threads help e2e than help the synchronized
    # microbench.  Order matters under the probe deadline below: the
    # combos screened before time runs out are the likeliest winners.
    pts = [int(pt_env)] if pt_env else [4, 2, 1]
    cms = [cm_env != "0"] if cm_env is not None else [True, False]
    shapes = [(batch_rows, nnz_cap)]
    if platform == "cpu":
        # no tunnel: extra put threads only time-slice the host core, and
        # compact wire spends host cycles to save a link that isn't there
        if not pt_env:
            pts = [1]
        if cm_env is None:
            cms = [False]
    elif "DMLC_BENCH_ROWS" not in os.environ:
        # the tunnelled device pays a per-put RPC latency that favours
        # bigger batches (TPU_DIAG: 64MB puts sustain the same MB/s as
        # 16MB, so amortizing more latency per put is ~free); which size
        # wins depends on the day's link, so the batch shape is part of
        # the probed config space, not a separate afterthought stage
        shapes.append((3 * batch_rows, 3 * nnz_cap))
        shapes.append((9 * batch_rows, 9 * nnz_cap))
    combos = [(p, c, s) for c in cms for s in shapes for p in pts]
    # soft deadline: the driver runs this under a finite timeout (r3:
    # 600 s probes), and on a collapsed link a full 18-combo screen can
    # eat it — a truncated probe with the best-so-far config beats a
    # killed process that falls back to CPU numbers.  Counted from
    # process start so data-gen/init time is included.  ONE value: the
    # screen gate and the timed-pair degrade below must agree.
    deadline = _T0 + float(os.environ.get("DMLC_BENCH_DEADLINE_S", "480"))
    if len(combos) > 1:
        # the tunnel decides: probe transfer streams × wire compaction ×
        # batch shape, keep the winning config for the timed runs; a config
        # that fails outright (e.g. a lowering quirk on the real backend)
        # scores 0 instead of killing the bench
        def probe_once(c):
            try:
                return run_once(c[0], c[1], *c[2])
            except Exception as e:  # noqa: BLE001
                log(f"  config pt={c[0]},compact={int(c[1])},"
                    f"rows={c[2][0]} failed: {type(e).__name__}: {e}")
                return 0.0

        # warm each distinct compiled program first so one-time jit compiles
        # (seconds each on a TPU) land in a discarded pass, not in a
        # config's score; put_threads changes no compilation, so one warm
        # pass per (compact, shape) pair suffices.  Deadline-gated like
        # the screen: on a collapsed link even warm passes take minutes,
        # and blowing the whole budget before the first scored combo would
        # recreate the killed-process outcome the deadline exists to avoid
        for key in dict.fromkeys((c[1], c[2]) for c in combos):
            if time.monotonic() > deadline:
                log("  probe deadline hit during warm-up")
                break
            probe_once((pts[0],) + key)
        # screen-then-confirm: single timings on the shared host + tunnel
        # carry one-sided noise (transient stalls), so the top screened
        # configs get a second run and score by their BEST — a single noisy
        # sample once mis-picked the batch shape by 1.5x (r3 harvest log)
        probe = {}
        for c in combos:
            if time.monotonic() > deadline:
                log(f"  probe deadline hit after {len(probe)}/"
                    f"{len(combos)} combos")
                break
            probe[c] = probe_once(c)
        for c in sorted((c for c, v in probe.items() if v > 0),
                        key=probe.get, reverse=True)[:3]:
            if time.monotonic() > deadline:
                break
            probe[c] = max(probe[c], probe_once(c))
        viable = {c: v for c, v in probe.items() if v > 0}
        if viable:
            pt, cm, shape = max(viable, key=viable.get)
        else:
            # nothing screened (deadline before combo 1): take the
            # best-guess-first combo, not a hardcoded worst guess
            pt, cm, shape = combos[0]
            log("  no combos screened — using best-guess config "
                f"pt={pt} compact={int(cm)} rows={shape[0]}")
        log("  config probe: " + " ".join(
            f"pt={k[0]},compact={int(k[1])},rows={k[2][0]}:{v:.1f}MB/s"
            for k, v in probe.items())
            + f" → pt={pt} compact={int(cm)} rows={shape[0]}")
    else:
        (pt, cm, shape), = combos
        run_once(pt, cm, *shape)  # warm-up: compile/caches
    # 5 timed pairs on the tunnelled device, 3 on cpu: the link drifts
    # 1.7-2.6x within a window and r04's 3-run phase landed entirely inside
    # one collapse (137-187 MB/s timed vs 467 probe minutes earlier) — more
    # pairs cost ~1 min of grant and bound the weather's leverage.
    # Degrade past the deadline: keep timing pairs only while the budget
    # lasts, with a floor of 3 on tpu (3 measured pairs in the driver's
    # budget beat 5 pairs killed mid-run with no JSON at all).  Checked
    # INSIDE the loop too — a link collapse can start between pairs.
    npairs = 5 if platform == "tpu" else 3
    if platform == "tpu" and time.monotonic() > deadline:
        log("  deadline spent before timed runs — 3 pairs instead of 5")
        npairs = 3
    runs = []
    for _ in range(npairs):
        if (platform == "tpu" and len(runs) >= 3
                and time.monotonic() > deadline):
            log(f"  deadline passed after {len(runs)} pairs — stopping")
            break
        runs.append(run_once(pt, cm, *shape))
        if interleave is not None:
            # reference run INSIDE the same minute as ours: the shared
            # host/tunnel drifts 1.7-2.6x within one window (TPU_DIAG
            # r03/r04), so ours-then-baseline phases sample different
            # weather and vs_baseline becomes luck; pairing them samples
            # the same weather for both sides
            interleave()
    spread = (max(runs) - min(runs)) / max(runs)
    log(f"  timed runs (pt={pt}, compact={int(cm)}, rows={shape[0]}): "
        + ", ".join(f"{r:.1f}" for r in runs) + f" MB/s, spread {spread:.0%}")
    # persist the winner (VERDICT r4 #2): DeviceLoader's "auto" knobs and
    # the suite's ingest configs inherit it so untuned defaults stop
    # wasting the probe's findings (r4: 20.2 vs 72 MB/s in one window)
    if not platform_override:  # never persist from an override/test run
        try:
            from dmlc_core_tpu.pipeline.tuned import save_tuned
            save_tuned({"platform": platform, "put_threads": pt,
                        "wire_compact": cm, "batch_rows": shape[0],
                        "nnz_cap": shape[1],
                        "mbps": round(sum(runs) / len(runs), 1)})
            log(f"  tuned config persisted for platform={platform}")
        except Exception as e:  # noqa: BLE001 — tuning is advisory
            log(f"  tuned-config persist failed: {e}")
    return sum(runs) / len(runs), runs, (pt, cm, shape[0]), platform


def main() -> None:
    # persistent jit cache: the per-bucket unpack programs compile once per
    # image, not once per invocation
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".jax_cache"))
    gen_data()
    require_tpu = os.environ.get("DMLC_REQUIRE_TPU") == "1"
    if require_tpu:
        # retry-loop mode: skip the pre-probe baseline entirely.  A
        # baseline measured while the probe retries for tens of minutes
        # races whatever else the host happens to run (observed r03: a
        # depressed pre-probe baseline flattering vs_baseline by ~2x);
        # instead the reference runs are interleaved BETWEEN our timed
        # runs inside the granted window — the grant is held, the chip is
        # idle, the host conditions are those of the measurement itself.
        base1 = 0.0
        if not probe_tpu():
            log("DMLC_REQUIRE_TPU=1 and no TPU → exiting 9")
            sys.exit(9)
    else:
        base1 = measure_reference()
    if not require_tpu and not probe_tpu():
        force_cpu()
    # reference runs are INTERLEAVED with our timed runs (same minutes,
    # same host+tunnel weather) — ours-then-baseline phases let the 1.7-2.6x
    # within-window drift masquerade as a speed delta in either direction
    refs: list = []
    try:
        value, runs, (put_threads, compact, rows_used), platform = (
            measure_ours(
                interleave=lambda: refs.append(measure_reference())))
    except Exception as e:  # noqa: BLE001
        # a grant that dies MID-timed-runs raises out of the device path;
        # the driver must still get a JSON line, so degrade to the CPU
        # pipeline (never silently: the platform field says cpu) unless
        # the artifact is required to be TPU-only.  The fallback must be a
        # fresh PROCESS: jax caches initialized backends
        # (xla_bridge.backends() short-circuits once populated), so an
        # in-process force_cpu() here would re-run on the same dead
        # backend — or worse, mislabel TPU-backend numbers as cpu.
        if require_tpu:
            raise
        log(f"device path failed mid-bench ({type(e).__name__}: {e}) "
            "→ re-running on CPU in a fresh process")
        env = dict(os.environ)
        env["DMLC_FORCE_CPU"] = "1"
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             env=env, timeout=3600, capture_output=True,
                             text=True)
        sys.stderr.write(out.stderr)
        line = next((ln for ln in reversed(out.stdout.strip().splitlines())
                     if ln.startswith("{")), None)
        if out.returncode != 0 or line is None:
            raise RuntimeError(
                f"cpu fallback rerun failed rc={out.returncode}") from e
        print(line)
        return
    bases = [b for b in ([base1] + refs) if b > 0] or [FALLBACK_BASELINE_MBS]
    baseline = sum(bases) / len(bases)
    log("baseline samples: " + ", ".join(f"{b:.1f}" for b in bases)
        + f" MB/s → using {baseline:.1f}")
    out = {
        "metric": "libsvm_ingest_to_device_batches",
        "value": round(value, 2),
        "unit": "MB/s",
        "vs_baseline": round(value / baseline, 3),
        "platform": platform,
        "runs": [round(r, 2) for r in runs],
        "put_threads": put_threads,
        "wire_compact": compact,
        "batch_rows": rows_used,
        "baselines_interleaved": [round(b, 1) for b in refs],
        # cpu path only (0.0 under DMLC_REQUIRE_TPU): recorded so
        # value/mean(recorded baselines) reproduces vs_baseline exactly
        "baseline_preprobe": round(base1, 1),
    }
    if platform == "tpu":
        # daemon thread + bounded join: the probe is optional context, and
        # this link's documented failure mode is a HANG (r03: one RPC
        # pending >1h) — a wedged tunnel here must not forfeit the
        # driver's JSON line for an otherwise-complete run
        import threading
        box = [0.0]

        def _probe():
            box[0] = measure_link_verified()

        th = threading.Thread(target=_probe, daemon=True)
        th.start()
        th.join(timeout=90)
        link = box[0] if not th.is_alive() else 0.0
        if th.is_alive():
            log("link probe still running at 90s — omitting")
        if link > 0:
            # context the ratio needs on tunnel hardware: the reference
            # binary parses host-locally and never crosses a link, so when
            # the verified link rate is below the host parse rate,
            # vs_baseline reports link weather, not pipeline quality
            # (docs/perf.md "What the read-back fix re-based").  The
            # driver-recorded artifact carries the evidence inline.
            out["link_mbps_verified"] = round(link, 1)
            out["value_over_link"] = round(value / link, 2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
